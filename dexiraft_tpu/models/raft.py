"""RAFT — the iterative optical-flow estimator, TPU-first.

Re-design of the reference model family (core/raft.py and its raft_1..raft_4
variants, SURVEY.md §2.5) as one Flax module driven by RAFTConfig:

  v1 'raft'      image stream only (core/raft_1.py)
  v2 'early'     6-ch early fusion, edges from data (core/raft_2.py)
  v3 'separate'  dual stream, edges from data, decoupled updates +
                 RefineFlow fusion (core/raft_3.py, output-width bug fixed)
  v4 'early'+embed_dexined   10-ch early fusion, embedded DexiNed (core/raft_4.py)
  v5 'dual'+embed_dexined    dual stream, frozen DexiNed, shared update block,
                 coupled update coords1 += Δflow + Δeflow (core/raft.py:183)

TPU-first design choices (vs. the reference's Python loop over CUDA calls):
  * the refinement loop is nn.scan (lax.scan) with weights broadcast — all
    iterations compile into ONE on-device graph; `iters` is static.
  * NHWC layouts; under mixed_precision encoders/update run in bf16 while
    the correlation volume stays fp32 (mirrors core/raft.py:134-148).
  * the correlation pyramid is a pytree threaded through the scan carry —
    XLA hoists it as loop-invariant.
  * coords are stop_gradient'ed at each iteration start, matching the
    reference's per-iteration detach (core/raft.py:170-171).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from dexiraft_tpu.config import RAFTConfig
from dexiraft_tpu.models.dexined import DexiNed, stack_edge_maps
from dexiraft_tpu.models.extractor import BasicEncoder, SmallEncoder
from dexiraft_tpu.models.update import BasicUpdateBlock, RefineFlow, SmallUpdateBlock
from dexiraft_tpu.ops.corr import build_corr_pyramid
from dexiraft_tpu.ops.local_corr import build_local_corr
from dexiraft_tpu.ops.grid import coords_grid, upflow8
from dexiraft_tpu.ops.upsample import upsample_flow_convex


def _normalize(img: jax.Array) -> jax.Array:
    """[0, 255] -> [-1, 1] (core/raft.py:104-105)."""
    return 2.0 * (img / 255.0) - 1.0


class RAFTStep(nn.Module):
    """One refinement iteration; scanned with params broadcast.

    Both streams of the dual/separate variants ride ONE batch: the edge
    stream is concatenated on the batch axis (the reference's two
    update-block calls share a single update_block, core/raft.py:179-180,
    so one call on batch 2B is the same math in half the dispatches — and
    every correlation-lookup matmul runs at double batch instead of twice).

    ``emit`` selects the scan output: per-iteration upsampled flows for
    training (sequence_loss consumes all of them, train.py:48-73), nothing
    in test mode — the final flow is upsampled ONCE after the scan from
    the carried mask (test_mode returns only the last prediction,
    core/raft.py:194-197).
    """

    cfg: RAFTConfig
    dtype: Any = jnp.float32
    emit: bool = True

    @nn.compact
    def __call__(self, carry: Dict[str, Any], _, consts: Dict[str, Any]):
        cfg = self.cfg
        if cfg.small:
            update_block = SmallUpdateBlock(hidden_dim=cfg.hidden_dim, dtype=self.dtype)
        else:
            update_block = BasicUpdateBlock(hidden_dim=cfg.hidden_dim, dtype=self.dtype)

        pyr = consts["pyr"]
        dual = cfg.has_edge_stream
        b = pyr.batch // 2 if dual else pyr.batch
        coords0 = coords_grid(b, pyr.ht, pyr.wd)

        coords1 = jax.lax.stop_gradient(carry["coords1"])  # (2B or B, h, w, 2)
        flow = coords1 - jnp.concatenate([coords0, coords0], 0) if dual \
            else coords1 - coords0
        if cfg.fused_update:
            # fused step (config.fused_update): the lookup and the motion
            # encoder's 1x1 corr conv run in ONE Pallas kernel inside the
            # update block — the (B, H, W, L*win^2) corr features never
            # materialize in HBM, which also makes remat_lookup moot here
            # (the fused VJP recomputes through the XLA reference anyway)
            net, up_mask, delta = update_block(
                carry["net"], consts["inp"], None, flow,
                pyr=pyr, coords=coords1)
        else:
            if cfg.remat_lookup and not cfg.remat:
                # recompute the lookup in backward instead of storing its
                # intermediates (the per-iteration hat matrices dominate
                # training memory — config.py remat_lookup). The pyramid
                # is passed as an argument so its gradients flow
                # normally; prevent_cse=False matches the full-remat
                # scan convention (the scan already rules out the CSE
                # hazard)
                corr = jax.checkpoint(lambda p, c: p(c),
                                      prevent_cse=False)(pyr, coords1)
            else:
                corr = pyr(coords1)
            net, up_mask, delta = update_block(carry["net"], consts["inp"],
                                               corr, flow)
        delta = delta.astype(jnp.float32)

        if dual:
            delta_flow, delta_eflow = delta[:b], delta[b:]
            ic, ec = coords1[:b], coords1[b:]
            if cfg.variant == "dual":
                # coupled update: edge deltas injected into the image flow
                # (core/raft.py:183-184)
                ic = ic + delta_flow + delta_eflow
                ec = ec + delta_eflow
            else:  # 'separate' (v3): decoupled (core/raft_3.py:160-161)
                ic = ic + delta_flow
                ec = ec + delta_eflow
            coords1 = jnp.concatenate([ic, ec], 0)
        else:
            coords1 = coords1 + delta

        carry = {**carry, "coords1": coords1, "net": net}

        if not self.emit:
            # test mode: keep only what the post-scan upsample needs
            carry["up_mask"] = up_mask
            return carry, None

        prediction = self._predict(cfg, coords1, coords0, up_mask, b)
        return carry, prediction

    def _predict(self, cfg, coords1, coords0, up_mask, b):
        if cfg.has_edge_stream:
            flow_up = _upsample(coords1[:b] - coords0,
                                None if up_mask is None else up_mask[:b])
            if cfg.variant == "separate":
                eflow_up = _upsample(coords1[b:] - coords0,
                                     None if up_mask is None else up_mask[b:])
                return RefineFlow(dtype=self.dtype)(
                    flow_up, eflow_up).astype(jnp.float32)
            return flow_up
        return _upsample(coords1 - coords0, up_mask)


def _upsample(flow: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
    if mask is None:  # small model has no mask head (core/raft.py:187-190)
        return upflow8(flow)
    return upsample_flow_convex(flow.astype(jnp.float32), mask.astype(jnp.float32))


class RAFT(nn.Module):
    """Full model: encoders + correlation pyramids + scanned refinement.

    Three entry modes share ONE param tree (checkpoints interchange):

      mode="pair"    (default) the monolithic two-frame forward — the
                     reference behavior, byte-identical to the
                     pre-split implementation (both frames ride one
                     batched encoder call).
      mode="encode"  per-FRAME encoder stage: fnet + cnet (and the
                     edge-stream efnet/ecnet twins) on a single frame,
                     returning the feature dict a later refinement can
                     consume. The streaming engine runs this ONCE per
                     new frame and pulls the previous frame's features
                     from the session carry — half the encoder FLOPs of
                     chained pair calls.
      mode="step"    refinement from two feature dicts (features1 is
                     the EARLIER frame — its ctx seeds the GRU) —
                     pyramid build + scanned update loop, same returns
                     as mode="pair".

    In test mode the split composition equals the monolithic call to
    float tolerance: the only difference is batched-vs-per-frame
    encoder calls, and every encoder norm is per-sample there (instance
    norm; BatchNorm on running stats). Parity is pinned in
    tests/test_zzvideo.py.
    """

    cfg: RAFTConfig = RAFTConfig()

    # ---- shared construction helpers (called inside the compact ctx) ----

    def _encoders(self, dtype):
        """The four encoder modules with their historical pinned names —
        both encode paths MUST construct them identically or the param
        tree forks between fused and split serving."""
        cfg = self.cfg
        hdim, cdim = cfg.hidden_dim, cfg.context_dim
        Encoder = SmallEncoder if cfg.small else BasicEncoder
        enc_norm = "instance"
        ctx_norm = "none" if cfg.small else "batch"
        fnet = Encoder(cfg.fnet_dim, enc_norm, cfg.dropout, dtype,
                       name="fnet")
        cnet = Encoder(hdim + cdim, ctx_norm, cfg.dropout, dtype,
                       name="cnet")
        efnet = ecnet = None
        if cfg.has_edge_stream:
            if cfg.variant == "dual":
                # v5: dedicated 7-channel edge encoders (core/raft.py:61-71)
                efnet = Encoder(cfg.fnet_dim, enc_norm, cfg.dropout, dtype,
                                name="efnet")
                ecnet = Encoder(hdim + cdim, ctx_norm, cfg.dropout, dtype,
                                name="ecnet")
            else:
                # v3: image and edge streams share fnet/cnet
                # (core/raft_3.py:110-127)
                efnet, ecnet = fnet, cnet
        return fnet, cnet, efnet, ecnet

    def _dexined(self, dtype):
        # name pinned to the historical auto-name so the pair and
        # per-frame paths bind the same frozen extractor params
        return DexiNed(dtype=dtype, upconv=self.cfg.dexined_upconv,
                       name="DexiNed_0")

    def _encode_pair(self, image1, image2, edges1, edges2, train, bn_train,
                     dtype):
        """The monolithic encoder stage: both frames through ONE batched
        call per encoder (better MXU utilization than two passes).
        Returns the two per-frame feature dicts _refine consumes; only
        frame 1 carries ctx (the GRU seeds from the earlier frame)."""
        cfg = self.cfg
        image1 = _normalize(image1.astype(jnp.float32))
        image2 = _normalize(image2.astype(jnp.float32))

        em1 = em2 = None
        if cfg.embed_dexined:
            # frozen edge extraction: raw logits, gradients stopped — the
            # no_grad contract of core/raft.py:111-123; under
            # mixed_precision the frozen extractor runs in bf16 like the
            # encoders — the reference keeps it fp32 only because it sits
            # outside the autocast region (docs/parity.md)
            both = jnp.concatenate([image1, image2], axis=0)
            maps = stack_edge_maps(self._dexined(dtype)(both, train=False))
            maps = jax.lax.stop_gradient(maps.astype(jnp.float32))
            em1, em2 = jnp.split(maps, 2, axis=0)
        elif cfg.variant in ("early", "separate"):
            if edges1 is None or edges2 is None:
                raise ValueError(
                    f"variant {cfg.variant!r} without embed_dexined requires "
                    "data-supplied edges1/edges2"
                )
            em1 = _normalize(edges1.astype(jnp.float32))
            em2 = _normalize(edges2.astype(jnp.float32))

        if cfg.variant == "early":
            image1 = jnp.concatenate([image1, em1], axis=-1)
            image2 = jnp.concatenate([image2, em2], axis=-1)
            em1 = em2 = None

        fnet, cnet, efnet, ecnet = self._encoders(dtype)
        fmap1, fmap2 = fnet((image1.astype(dtype), image2.astype(dtype)),
                            train=train, bn_train=bn_train)
        f1: Dict[str, Any] = {"fmap": fmap1.astype(jnp.float32),
                              "ctx": cnet(image1.astype(dtype), train=train,
                                          bn_train=bn_train)}
        f2: Dict[str, Any] = {"fmap": fmap2.astype(jnp.float32)}
        if cfg.has_edge_stream:
            fem1, fem2 = efnet((em1.astype(dtype), em2.astype(dtype)),
                               train=train, bn_train=bn_train)
            f1["efmap"] = fem1.astype(jnp.float32)
            f2["efmap"] = fem2.astype(jnp.float32)
            f1["ectx"] = ecnet(em1.astype(dtype), train=train,
                               bn_train=bn_train)
        return f1, f2

    def _encode_frame(self, image, edges, train, bn_train, dtype):
        """Per-frame encoder stage (mode="encode"): everything a frame
        contributes to ANY pair it joins — fmap (as frame 1 or 2) AND
        ctx (consumed only when it is the earlier frame). Computing ctx
        unconditionally is what makes the streaming carry work: frame t
        was frame 2 of pair (t-1, t) and becomes frame 1 of (t, t+1)
        without re-encoding."""
        cfg = self.cfg
        image = _normalize(image.astype(jnp.float32))
        em = None
        if cfg.embed_dexined:
            maps = stack_edge_maps(self._dexined(dtype)(image, train=False))
            em = jax.lax.stop_gradient(maps.astype(jnp.float32))
        elif cfg.variant in ("early", "separate"):
            if edges is None:
                raise ValueError(
                    f"variant {cfg.variant!r} without embed_dexined requires "
                    "a data-supplied edge frame in mode='encode'")
            em = _normalize(edges.astype(jnp.float32))
        if cfg.variant == "early":
            image = jnp.concatenate([image, em], axis=-1)
            em = None

        fnet, cnet, efnet, ecnet = self._encoders(dtype)
        out: Dict[str, Any] = {
            "fmap": fnet(image.astype(dtype), train=train,
                         bn_train=bn_train).astype(jnp.float32),
            "ctx": cnet(image.astype(dtype), train=train,
                        bn_train=bn_train),
        }
        if cfg.has_edge_stream:
            out["efmap"] = efnet(em.astype(dtype), train=train,
                                 bn_train=bn_train).astype(jnp.float32)
            out["ectx"] = ecnet(em.astype(dtype), train=train,
                                bn_train=bn_train)
        return out

    @nn.compact
    def __call__(
        self,
        image1: Optional[jax.Array],
        image2: Optional[jax.Array] = None,
        edges1: Optional[jax.Array] = None,
        edges2: Optional[jax.Array] = None,
        iters: int = 12,
        flow_init: Optional[jax.Array] = None,
        train: bool = False,
        freeze_bn: bool = False,
        test_mode: bool = False,
        mode: str = "pair",
        features1: Optional[Dict[str, Any]] = None,
        features2: Optional[Dict[str, Any]] = None,
        adaptive: bool = False,
        iter_budget: Optional[jax.Array] = None,
    ):
        """Estimate flow between two (B, H, W, 3) [0,255] frames.

        edges1/edges2: (B, H, W, 3) edge images for the v2/v3 variants
        (data-supplied edge contract); ignored when embed_dexined=True.

        mode="encode" consumes only (image1 [, edges1]) and returns the
        per-frame feature dict; mode="step" consumes features1/features2
        (dicts from mode="encode" or the streaming carry) and ignores
        the images. See the class docstring.

        Returns stacked per-iteration upsampled flows (iters, B, H, W, 2),
        or (flow_low, flow_up) in test_mode (core/raft.py:194-197).

        adaptive=True (inference only): the fixed scan is replaced by a
        lax.while_loop with a per-item convergence gate — an item
        freezes (masked no-op update, carry preserved) once the mean
        per-pixel L2 norm of its 1/8-res flow delta drops below
        cfg.converge_tol, and the loop exits when every item is done or
        ``iter_budget`` (a TRACED int32 scalar, clamped to [0, iters] —
        one compiled executable serves every budget) expires. Returns
        (flow_low, flow_up, iters_used[B], final_delta[B]). The train
        path is untouched; variant='separate' is refused (its RefineFlow
        head must stay inside the emitting scan for parameter-path
        stability, which the non-emitting while_loop cannot host).
        """
        cfg = self.cfg
        if adaptive:
            if not test_mode:
                raise ValueError(
                    "adaptive=True is an inference path: it needs "
                    "test_mode=True (the sequence loss consumes every "
                    "iteration's prediction — early exit has no training "
                    "meaning, and the scan+remat train path stays as-is)")
            if cfg.variant == "separate":
                raise ValueError(
                    "adaptive=True does not support variant='separate': "
                    "its RefineFlow fusion head lives INSIDE the scanned "
                    "step (emit=True even in test mode, models/raft.py) "
                    "and the adaptive while_loop drives the non-emitting "
                    "step; use v1/v2/v4/v5 or the fixed-iters path")
        elif iter_budget is not None:
            raise ValueError(
                "iter_budget only has meaning with adaptive=True (the "
                "fixed path compiles its iteration count statically)")
        # corr_impl/corr_dtype/fused_update combinations are refused at
        # CONFIG time (RAFTConfig.__post_init__) — by the time a config
        # reaches apply() they are known-valid. Only the runtime-
        # dependent refusals live here.
        if train and cfg.corr_dtype == "int8":
            raise ValueError(
                "corr_dtype='int8' is an inference format: the round() in "
                "quantization zeroes the fmap gradients, which would train "
                "the feature encoder silently dead. Use 'bf16' (or 'fp32') "
                "for training and 'int8' for eval/serve")
        if cfg.variant == "dual" and not cfg.embed_dexined:
            raise ValueError(
                "variant='dual' requires embed_dexined=True (the v5 edge "
                "stream consumes DexiNed's 7 logit maps; use raft_v5())"
            )
        dtype = jnp.bfloat16 if cfg.mixed_precision else jnp.float32
        # freeze_bn: post-chairs stages run BN on running stats (train.py:149-150)
        bn_train = train and not freeze_bn

        if mode == "encode":
            return self._encode_frame(image1, edges1, train, bn_train, dtype)
        if mode == "step":
            if features1 is None or features2 is None:
                raise ValueError(
                    "mode='step' needs features1 AND features2 (per-frame "
                    "dicts from mode='encode'; features1 is the EARLIER "
                    "frame)")
        elif mode == "pair":
            if image1 is None or image2 is None:
                # images became Optional for the split modes; fail the
                # monolithic path loudly instead of a NoneType
                # AttributeError deep inside _normalize
                raise ValueError(
                    "mode='pair' needs image1 AND image2 (two (B, H, W, "
                    "3) frames; mode='encode' takes one, mode='step' "
                    "takes feature dicts)")
            features1, features2 = self._encode_pair(
                image1, image2, edges1, edges2, train, bn_train, dtype)
        else:
            raise ValueError(f"unknown mode {mode!r}; expected "
                             "'pair' | 'encode' | 'step'")

        hdim = cfg.hidden_dim

        def build_pyr(f1, f2):
            # plugin seam (BASELINE.json): materialized MXU volume vs
            # on-demand local correlation (the alt_cuda_corr analog);
            # corr_dtype sets the pyramid's STORAGE precision on both
            # (ops/quant.py — dequantized inside the lookup)
            if cfg.corr_impl == "allpairs":
                return build_corr_pyramid(f1, f2, cfg.corr_levels, cfg.radius,
                                          dtype=cfg.corr_dtype)
            return build_local_corr(f1, f2, cfg.corr_levels, cfg.radius,
                                    row_chunk=cfg.corr_row_chunk,
                                    dtype=cfg.corr_dtype,
                                    kernel=("xla" if cfg.corr_impl == "local"
                                            else cfg.corr_impl))

        fmap1, fmap2 = features1["fmap"], features2["fmap"]
        ctx = features1["ctx"]
        net = jnp.tanh(ctx[..., :hdim])
        inp = nn.relu(ctx[..., hdim:])

        b, h8, w8 = fmap1.shape[0], fmap1.shape[1], fmap1.shape[2]
        coords0 = coords_grid(b, h8, w8)
        coords1 = coords_grid(b, h8, w8)
        if flow_init is not None:
            coords1 = coords1 + flow_init

        if cfg.has_edge_stream:
            fem1, fem2 = features1["efmap"], features2["efmap"]
            ectx = features1["ectx"]
            # both streams share one batch axis: one pyramid build, one
            # lookup and one update-block call per iteration (RAFTStep)
            pyr = build_pyr(jnp.concatenate([fmap1, fem1], 0),
                            jnp.concatenate([fmap2, fem2], 0))
            coords1 = jnp.concatenate([coords1, coords_grid(b, h8, w8)], 0)
            net = jnp.concatenate([net, jnp.tanh(ectx[..., :hdim])], 0)
            inp = jnp.concatenate([inp, nn.relu(ectx[..., hdim:])], 0)
        else:
            pyr = build_pyr(fmap1, fmap2)

        carry: Dict[str, Any] = {"coords1": coords1, "net": net}
        consts = {"pyr": pyr, "inp": inp}

        # per-iteration upsampled flows are only consumed by the sequence
        # loss; in test mode (except v3, whose RefineFlow head must stay
        # inside the scanned module for parameter-path stability) the scan
        # emits nothing and the final flow is upsampled once afterwards
        emit = (not test_mode) or cfg.variant == "separate"
        if not emit:
            if cfg.small:
                carry["up_mask"] = None
            else:
                nb = 2 * b if cfg.has_edge_stream else b
                carry["up_mask"] = jnp.zeros((nb, h8, w8, 64 * 9), dtype)

        if adaptive:
            # adaptive implies test_mode and not 'separate', so emit is
            # False here and the carry already holds the up_mask slot
            return self._adaptive_refine(carry, consts, coords0, b, iters,
                                         iter_budget, dtype)

        step_cls = RAFTStep
        if cfg.remat:
            # recompute each iteration's activations in backward instead
            # of storing iters x (GRU state + corr features) in HBM;
            # remat_policy="dots_saveable" keeps matmul/conv outputs
            # saved (cheap elementwise chains recompute) — the
            # intermediate point on the HBM/FLOPs axis (config.py)
            kw = {}
            if cfg.remat_policy == "dots_saveable":
                kw["policy"] = jax.checkpoint_policies.dots_saveable
            step_cls = nn.remat(RAFTStep, prevent_cse=False, **kw)
        scan = nn.scan(
            step_cls,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=(0, nn.broadcast),
            length=iters,
            unroll=max(1, min(cfg.scan_unroll, iters)),
        )
        # pin the module name so parameter paths (and thus checkpoints and
        # interop name maps) are identical with and without remat
        carry, predictions = scan(cfg=cfg, dtype=dtype, emit=emit,
                                  name="ScanRAFTStep_0")(carry, None, consts)

        if test_mode:
            flow_low = carry["coords1"][:b] - coords0
            if emit:
                return flow_low, predictions[-1]
            flow_up = _upsample(
                flow_low,
                None if carry["up_mask"] is None else carry["up_mask"][:b])
            return flow_low, flow_up
        return predictions

    def _adaptive_refine(self, carry, consts, coords0, b, iters,
                         iter_budget, dtype):
        """Convergence-gated refinement (``adaptive=True``): an
        nn.while_loop over the SAME step module the scan path drives —
        the module name is pinned to "ScanRAFTStep_0" with params
        broadcast, so the parameter tree (and thus every checkpoint) is
        identical between the two drivers.

        Per-item gate: after each update, the item's flow delta at 1/8
        res (the image stream's coords1 movement) reduces to a mean
        per-pixel L2 norm; once it drops below cfg.converge_tol the item
        is DONE — subsequent iterations freeze its carry rows via a
        masked select (dual variants freeze the edge-stream row b+i
        together with its image row i), so a converged item's result is
        bit-identical to having stopped. The loop exits when every item
        is done or the traced ``iter_budget`` expires; with tol=0 the
        gate never fires (the norm is >= 0) and a full budget replays
        the scan path's update sequence exactly.

        Returns (flow_low, flow_up, iters_used[B], final_delta[B]):
        iters_used counts the updates each item actually applied;
        final_delta is the item's last pre-freeze delta norm (0.0 if
        the budget was 0 and no update ever ran).
        """
        cfg = self.cfg
        # no remat wrapper: this path never differentiates, and the
        # plain module binds the same "ScanRAFTStep_0" parameter paths
        step = RAFTStep(cfg=cfg, dtype=dtype, emit=False,
                        name="ScanRAFTStep_0")

        def finish(c, iters_used, final_delta):
            flow_low = c["coords1"][:b] - coords0
            flow_up = _upsample(
                flow_low,
                None if c["up_mask"] is None else c["up_mask"][:b])
            return flow_low, flow_up, iters_used, final_delta

        if self.is_initializing():
            # nn.while_loop cannot create variables inside its body; one
            # direct step call initializes the (broadcast) params — the
            # same tree the while_loop then closes over read-only
            c, _ = step(carry, None, consts)
            return finish(c, jnp.zeros((b,), jnp.int32),
                          jnp.zeros((b,), jnp.float32))

        budget = iters if iter_budget is None else iter_budget
        budget = jnp.clip(jnp.asarray(budget, jnp.int32), 0, iters)
        tol = jnp.float32(cfg.converge_tol)

        state = {
            "carry": carry,
            "done": jnp.zeros((b,), bool),
            "iters_used": jnp.zeros((b,), jnp.int32),
            "final_delta": jnp.zeros((b,), jnp.float32),
            "it": jnp.zeros((), jnp.int32),
        }

        def cond_fn(_mdl, s):
            return jnp.logical_and(s["it"] < budget,
                                   jnp.any(jnp.logical_not(s["done"])))

        def body_fn(mdl, s):
            old = s["carry"]
            new, _ = mdl(old, None, consts)
            # the convergence signal: how far this update moved the
            # IMAGE stream's 1/8-res flow, as a mean per-pixel L2 norm
            d = new["coords1"][:b] - old["coords1"][:b]
            dn = jnp.sqrt(jnp.sum(jnp.square(d), -1)).mean((1, 2))
            active = jnp.logical_not(s["done"])

            def freeze(o, n):
                m = active
                if n.shape[0] != b:
                    # dual variants: the edge-stream row rides (and
                    # freezes with) its image row
                    m = jnp.concatenate([active, active], 0)
                return jnp.where(m.reshape((-1,) + (1,) * (n.ndim - 1)),
                                 n, o)

            return {
                "carry": jax.tree.map(freeze, old, new),
                "done": jnp.logical_or(s["done"], dn < tol),
                "iters_used": s["iters_used"] + active.astype(jnp.int32),
                "final_delta": jnp.where(active, dn, s["final_delta"]),
                "it": s["it"] + 1,
            }

        state = nn.while_loop(cond_fn, body_fn, step, state)
        return finish(state["carry"], state["iters_used"],
                      state["final_delta"])
