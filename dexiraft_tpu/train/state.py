"""Train state: params + batch stats + optimizer state + step + PRNG key.

One pytree that the jitted step consumes and returns. Unlike the reference
(which checkpoints only model weights, train.py:189-190 — optimizer and
schedule restart on resume, SURVEY.md §5), the full state here round-trips
through checkpoints.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.struct
import jax
import jax.numpy as jnp
import optax

from dexiraft_tpu.config import RAFTConfig, TrainConfig
from dexiraft_tpu.models.raft import RAFT


@flax.struct.dataclass
class TrainState:
    """Dtype contract: `params`, `opt_state`, and `batch_stats` are fp32
    REGARDLESS of TrainConfig.precision — under the bf16 policy the model
    runs its mixed-precision path and flax casts per-op bf16 copies from
    the fp32 masters here, which are what the optimizer updates and
    checkpoints serialize. Checkpoints are therefore precision-portable:
    a run can switch policy on resume."""

    step: jax.Array  # scalar int32
    params: Any
    batch_stats: Any  # BatchNorm running stats ({} when encoders have none)
    opt_state: Any
    rng: jax.Array  # PRNG key threaded through steps (dropout / noise aug)

    @property
    def variables(self):
        return {"params": self.params, "batch_stats": self.batch_stats}


def model_inputs_shape(
    cfg: RAFTConfig, batch: int, image_size: Tuple[int, int]
) -> Tuple[Tuple[int, ...], Optional[Tuple[int, ...]]]:
    """(image shape, edge-image shape or None) for init/dummy batches."""
    h, w = image_size
    img = (batch, h, w, 3)
    edges = (batch, h, w, 3) if (cfg.variant in ("early", "separate") and not cfg.embed_dexined) else None
    return img, edges


def create_state(
    rng: jax.Array,
    cfg: RAFTConfig,
    tc: TrainConfig,
    batch_size: Optional[int] = None,
    image_size: Optional[Tuple[int, int]] = None,
) -> TrainState:
    """Initialize params (Kaiming/Xavier per module) and optimizer state.

    Init runs on small dummy shapes — RAFT is fully convolutional, so
    parameters are shape-independent of the training resolution.
    """
    model = RAFT(cfg)
    bs = batch_size if batch_size is not None else 1
    init_size = image_size if image_size is not None else (64, 64)
    img_shape, edge_shape = model_inputs_shape(cfg, bs, init_size)

    init_rng, state_rng = jax.random.split(rng)
    dummy = jnp.zeros(img_shape, jnp.float32)
    kwargs = {}
    if edge_shape is not None:
        e = jnp.zeros(edge_shape, jnp.float32)
        kwargs = dict(edges1=e, edges2=e)
    variables = model.init(init_rng, dummy, dummy, iters=1, train=False, **kwargs)

    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    tx = make_optimizer_from(tc)
    opt_state = tx.init(params)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=opt_state,
        rng=state_rng,
    )


def make_optimizer_from(tc: TrainConfig) -> optax.GradientTransformation:
    from dexiraft_tpu.train.optimizer import make_optimizer

    return make_optimizer(tc.lr, tc.num_steps, tc.wdecay, tc.epsilon, tc.clip)


def param_count(params: Any) -> int:
    return sum(int(x.size) for x in jax.tree.leaves(params))
