"""Metrics logger: running means -> stdout + JSONL + optional TensorBoard.

Reproduces the reference Logger (train.py:90-134): running means over
SUM_FREQ steps, a formatted "[step, lr] epe 1px 3px 5px" stdout line, and
TensorBoard scalars. Adds a machine-readable metrics.jsonl (the TPU plan's
observability upgrade, SURVEY.md §5) and an iters/sec meter — the
north-star throughput metric the reference never recorded.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional


class Logger:
    def __init__(
        self,
        sum_freq: int = 100,
        log_dir: Optional[str] = None,
        tensorboard: bool = True,
        model_iters: int = 12,
        pipeline_stats=None,
    ):
        self.sum_freq = sum_freq
        self.log_dir = log_dir
        self.model_iters = model_iters
        # data-pipeline fault counters (data.loader.PipelineStats): read
        # at every emit so skip/restart counts are visible IN the run's
        # log stream, not only in a post-mortem — the silent-degradation
        # analog of the divergence guard's loud rollback
        self.pipeline_stats = pipeline_stats
        self.total_steps = 0
        self.running: Dict[str, list] = {}
        self._tb = None
        self._jsonl = None
        self._t0 = time.perf_counter()
        self._steps_since = 0
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._jsonl = open(os.path.join(log_dir, "metrics.jsonl"), "a")
            if tensorboard:
                try:
                    from torch.utils.tensorboard import SummaryWriter

                    self._tb = SummaryWriter(log_dir)
                except Exception:
                    self._tb = None

    def push(self, metrics: Dict[str, float]) -> None:
        """Accumulate one step's metrics; emit every sum_freq steps.

        Device scalars are appended as-is — no device math, no host
        fetch — so push never blocks on the jitted step AND never
        dispatches an eager op (an eager `prev + v` would compile a tiny
        jit(add) executable on first use, tripping the strict-mode
        recompile sentinel; a `0.0 + v` seed would additionally be an
        implicit host->device transfer). The window is reduced on the
        host at _emit's one sanctioned sync.
        """
        self.total_steps += 1
        self._steps_since += 1
        for k, v in metrics.items():
            self.running.setdefault(k, []).append(v)
        if self.total_steps % self.sum_freq == 0:
            self._emit()

    def _emit(self) -> None:
        import jax  # deferred: keep module importable without jax

        n = max(self._steps_since, 1)
        # ONE explicit device->host fetch for the whole window (jaxlint
        # JL007): this is the loop's sanctioned sync point, and
        # device_get passes a strict transfer guard
        host = jax.device_get(self.running)
        means = {k: float(sum(float(x) for x in vs)) / n
                 for k, vs in host.items()}
        dt = time.perf_counter() - self._t0
        steps_per_sec = n / dt if dt > 0 else 0.0
        means["steps/sec"] = steps_per_sec
        means["iters/sec"] = steps_per_sec * self.model_iters

        pipeline = ""
        ps = self.pipeline_stats
        if ps is not None and ps.faults:
            # cumulative counts (not per-window deltas): an operator
            # grepping any single line sees the run's full damage
            pipeline = (f"  [pipeline: {ps.skipped_samples} skipped, "
                        f"{ps.retries} retries, {ps.dropped_batches} "
                        f"batches dropped, {ps.worker_restarts} "
                        f"worker restarts]")
            for k, v in ps.as_dict().items():
                means[f"pipeline/{k}"] = v

        lr = means.get("lr", 0.0)
        keys = [k for k in ("epe", "1px", "3px", "5px", "loss") if k in means]
        body = ", ".join(f"{means[k]:10.4f}" for k in keys)
        print(f"[{self.total_steps:6d}, {lr:10.7f}] {body}  ({steps_per_sec:.2f} steps/s){pipeline}")

        self._write(means, self.total_steps)
        self.running = {}
        self._steps_since = 0
        self._t0 = time.perf_counter()

    def rewind(self, step: int) -> None:
        """Align with a trainer rollback: drop the (possibly poisoned)
        accumulation window and rewind the step counter so subsequent
        emitted/checkpointed/validated step numbers agree again."""
        self.total_steps = step
        self.running = {}
        self._steps_since = 0
        self._t0 = time.perf_counter()

    def write_dict(self, results: Dict[str, float], step: Optional[int] = None) -> None:
        """Validation results (train.py:126-131)."""
        self._write(results, self.total_steps if step is None else step)

    def _write(self, scalars: Dict[str, float], step: int) -> None:
        if self._jsonl:
            self._jsonl.write(json.dumps({"step": step, **{k: float(v) for k, v in scalars.items()}}) + "\n")
            self._jsonl.flush()
        if self._tb:
            for k, v in scalars.items():
                self._tb.add_scalar(k, float(v), step)

    def close(self) -> None:
        if self._jsonl:
            self._jsonl.close()
        if self._tb:
            self._tb.close()
