"""Training layer: optimizer/schedule, train state, jitted step, checkpointing, logging."""

from dexiraft_tpu.train.optimizer import make_optimizer, onecycle_lr
from dexiraft_tpu.train.state import TrainState, create_state
from dexiraft_tpu.train.step import make_eval_step, make_train_step

__all__ = [
    "TrainState",
    "create_state",
    "make_eval_step",
    "make_optimizer",
    "make_train_step",
    "onecycle_lr",
]
