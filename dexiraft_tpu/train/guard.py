"""Divergence-guard decision logic, shared by both trainers.

The RAFT trainer (train_cli) checks on a step cadence and before every
checkpoint write; the DexiNed trainer (dexined_cli) checks at epoch end.
Both make the same decision — is this state poisoned, and if so, is a
rollback still allowed? — so the decision lives here once. The trainers
keep their own restore/log/rewind mechanics (those genuinely differ).

The poison verdict combines two signals: the loss (pre-update params;
the reference's only observable — its v3 run diverged from EPE 8.4 to
347 and kept logging, SURVEY.md §5) and ``state_finite``, the step's
post-update verdict (train.step.all_finite) that closes the one-step
blind spot a loss-only guard has.
"""

from __future__ import annotations

import math


class DivergenceGuard:
    """Counts rollbacks and decides poisoned/recoverable.

    Raises RuntimeError from ``consume_rollback`` when no valid rollback
    target exists or the budget is spent — persistent divergence needs a
    human (lower the lr or inspect the data).
    """

    def __init__(self, threshold: float = 1e4, max_rollbacks: int = 3):
        self.threshold = threshold
        self.max_rollbacks = max_rollbacks
        self.rollbacks = 0

    def poisoned(self, loss_v: float, state_ok: bool = True) -> bool:
        return (not math.isfinite(loss_v) or loss_v > self.threshold
                or not state_ok)

    def consume_rollback(self, loss_v: float, state_ok: bool,
                         where: str, last_saved,
                         ckpt_dir: "str | None" = None) -> str:
        """Spend one rollback or raise if unrecoverable.

        Returns (and, on abort, embeds in the RuntimeError) a message
        naming the checkpoint dir and restore step, so the operator can
        inspect the rolled-back state — `eval --model <dir>` it, diff
        its metrics — without reading the trainer's source to learn
        where the state went.
        """
        target = (f"step {last_saved}" if ckpt_dir is None
                  else f"{ckpt_dir} step {last_saved}")
        if last_saved is None or self.rollbacks >= self.max_rollbacks:
            raise RuntimeError(
                f"training diverged (loss {loss_v:.4g}, "
                f"state_finite={state_ok}) at {where}"
                + (" before this run saved any checkpoint"
                   if last_saved is None else
                   f" after {self.rollbacks} rollbacks; last good "
                   f"checkpoint: {target}")
                + "; lower the lr or inspect the data")
        self.rollbacks += 1
        return (f"restoring {target} "
                f"(rollback {self.rollbacks}/{self.max_rollbacks})")
