"""Orbax checkpointing: full train state + partial (curriculum) restore.

Upgrades the reference's torch.save(model.state_dict()) every 5k steps
(train.py:189-190): here params, BatchNorm stats, optimizer state, step,
and PRNG key all round-trip, so resume continues the OneCycle schedule
instead of restarting it (the reference's documented gap, SURVEY.md §5).

``restore_params_into`` reproduces load_state_dict(strict=False)
(train.py:143-144): stage-to-stage and architecture-drift loads keep every
leaf whose path and shape match and leave the rest freshly initialized.
"""

from __future__ import annotations

import atexit
import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from dexiraft_tpu.train.state import TrainState


_MANAGERS: "dict[str, ocp.CheckpointManager]" = {}


def _manager(directory: str, refresh: bool = True) -> ocp.CheckpointManager:
    """One live CheckpointManager per directory.

    Constructing and closing a fresh manager per save/restore is fine at
    VAL_FREQ=5000 but wasteful the moment the cadence tightens (each
    construction lists the directory and spins up orbax's async save
    machinery). Cached managers are reload()ed before READS so steps
    written by another process are still observed; writers pass
    refresh=False (a save needs no directory re-listing).
    """
    key = os.path.abspath(directory)
    mgr = _MANAGERS.get(key)
    if mgr is None:
        mgr = ocp.CheckpointManager(
            key, options=ocp.CheckpointManagerOptions(create=True))
        _MANAGERS[key] = mgr
    elif refresh and hasattr(mgr, "reload"):
        mgr.reload()
    return mgr


@atexit.register
def close_managers() -> None:
    """Close every cached manager (flushes pending async work).

    Registered atexit so long processes touching many directories (a
    pytest run's tmp dirs) don't leak orbax's per-manager machinery
    through interpreter shutdown; safe to call earlier by hand.
    """
    for mgr in _MANAGERS.values():
        mgr.close()
    _MANAGERS.clear()


def save_checkpoint(directory: str, state: TrainState, step: Optional[int] = None) -> None:
    """Write <directory>/<step>/ with the full state (blocking)."""
    mgr = _manager(directory, refresh=False)
    s = int(state.step) if step is None else int(step)
    mgr.save(s, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()


def latest_step(directory: str) -> Optional[int]:
    return _manager(directory).latest_step()


def restore_checkpoint(
    directory: str, template: TrainState, step: Optional[int] = None
) -> TrainState:
    """Restore a full TrainState; ``template`` supplies tree structure,
    shapes, and shardings (create one with create_state)."""
    mgr = _manager(directory)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    return mgr.restore(step, args=ocp.args.StandardRestore(abstract))


def restore_params_into(
    params: Any, restored_params: Any, verbose: bool = False
) -> Tuple[Any, list]:
    """strict=False load: graft every leaf whose path exists in both trees
    with matching shape; keep the fresh init elsewhere. Returns (merged,
    list of skipped/missing path strings)."""
    flat_new = {jax.tree_util.keystr(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(params)[0]}
    flat_old = {jax.tree_util.keystr(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(restored_params)[0]}

    skipped = []
    merged = dict(flat_new)
    for key, new_leaf in flat_new.items():
        old = flat_old.get(key)
        if old is not None and tuple(old.shape) == tuple(new_leaf.shape):
            merged[key] = old
        else:
            skipped.append(key)
    skipped += [k for k in flat_old if k not in flat_new]
    if verbose and skipped:
        print(f"[checkpoint] partial restore skipped {len(skipped)} leaves: {skipped[:8]}…")

    # rebuild the tree: map leaves back by path order
    paths, treedef = jax.tree_util.tree_flatten_with_path(params)
    leaves = [merged[jax.tree_util.keystr(kp)] for kp, _ in paths]
    return jax.tree_util.tree_unflatten(treedef, leaves), skipped
