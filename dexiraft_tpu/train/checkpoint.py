"""Orbax checkpointing: async full-state saves + partial (curriculum) restore.

Upgrades the reference's torch.save(model.state_dict()) every 5k steps
(train.py:189-190): here params, BatchNorm stats, optimizer state, step,
and PRNG key all round-trip, so resume continues the OneCycle schedule
instead of restarting it (the reference's documented gap, SURVEY.md §5).

``save_checkpoint(block=False)`` is the pod-grade save path: the state is
snapshotted synchronously (the ONLY part the step loop waits for, and
what makes the handoff safe against the donated train step invalidating
the device buffers — replicated leaves device_get to host, fsdp-sharded
leaves take an on-device per-shard copy that orbax's sharding-aware
serializer then writes one addressable shard per host) and the flush
(serialize + disk write + atomic commit) runs on a single background
thread. ``wait_pending`` is the barrier, taken before anything that
reads or mutates the directory — the next save, a rollback restore,
retention GC, or exit — and it reports how long the caller actually
blocked vs how long the flush took, so the overlap win is measurable
(train_cli surfaces both in the logger).

Atomicity is orbax's: a step flushes into ``<step>.orbax-checkpoint-tmp-*``
and is renamed to ``<step>/`` only on commit, so a crash mid-flush leaves
the previous committed step as the newest restorable one —
``resilience.verify.restore_verified`` lands there (pinned by the
kill-mid-flush chaos phase and tests/test_zzresilience.py).

PRNG keys: new-style typed keys (``jax.random.key``) refuse numpy
conversion, which used to crash orbax's serializer. ``_keys_to_data`` /
``_data_to_keys`` are the dtype-preserving leaf handler: typed keys are
saved as their uint32 key data and re-wrapped on restore with the
template leaf's impl, so both key styles round-trip bit-exactly.

``restore_params_into`` reproduces load_state_dict(strict=False)
(train.py:143-144): stage-to-stage and architecture-drift loads keep every
leaf whose path and shape match and leave the rest freshly initialized.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import orbax.checkpoint as ocp

from dexiraft_tpu.analysis.locks import OrderedLock
from dexiraft_tpu.train.state import TrainState


_MANAGERS: "dict[str, ocp.CheckpointManager]" = {}

# one in-flight flush per directory: step, submit time, and the future
# running _flush on _EXECUTOR. The single-worker executor serializes all
# background manager access; foreground access is safe because every
# read/mutate path below takes the wait_pending barrier first.
_PENDING: "dict[str, dict]" = {}
_STATS: "dict[str, dict]" = {}
_EXECUTOR: Optional[ThreadPoolExecutor] = None
# guards the pending/stats registries only — never held across a flush
# wait (wait_pending pops under the lock, then blocks on the future
# outside it; the flush thread itself never touches this lock)
_LOCK = OrderedLock("train.checkpoint.pending")

# --- test/chaos seams (resilience.chaos, tests/test_zzresilience.py) -----
# flush_hold: when set to an Event, the background flush waits on it
# before touching orbax — tests use it to pin "a flush is in flight"
# without racing real disk latency. chaos kill: the next async save
# hard-exits the process once the flush has started (a real mid-flush
# crash; os._exit skips atexit, so nothing downstream cleans up).
flush_hold: Optional[threading.Event] = None
_chaos_kill_next_flush = False


def chaos_kill_next_flush() -> None:
    """Arm the mid-flush crash: the next ``save_checkpoint`` initiates its
    flush and then ``os._exit``s while it is in flight (chaos injector —
    see resilience.chaos.parse_spec, spec ``kill_mid_flush@N``)."""
    global _chaos_kill_next_flush
    _chaos_kill_next_flush = True


def _executor() -> ThreadPoolExecutor:
    global _EXECUTOR
    if _EXECUTOR is None:
        _EXECUTOR = ThreadPoolExecutor(max_workers=1,
                                       thread_name_prefix="ckpt-flush")
    return _EXECUTOR


def _manager(directory: str, refresh: bool = True) -> ocp.CheckpointManager:
    """One live CheckpointManager per directory.

    Constructing and closing a fresh manager per save/restore is fine at
    VAL_FREQ=5000 but wasteful the moment the cadence tightens (each
    construction lists the directory and spins up orbax's async save
    machinery). Cached managers are reload()ed before READS so steps
    written by another process are still observed; writers pass
    refresh=False (a save needs no directory re-listing).
    """
    key = os.path.abspath(directory)
    mgr = _MANAGERS.get(key)
    if mgr is None:
        mgr = ocp.CheckpointManager(
            key, options=ocp.CheckpointManagerOptions(create=True))
        _MANAGERS[key] = mgr
    elif refresh and hasattr(mgr, "reload"):
        mgr.reload()
    return mgr


@atexit.register
def close_managers() -> None:
    """Flush pending async saves and close every cached manager.

    Registered atexit so long processes touching many directories (a
    pytest run's tmp dirs) don't leak orbax's per-manager machinery
    through interpreter shutdown — and so an in-flight async save is
    always committed before a clean exit (the "exit" barrier); safe to
    call earlier by hand.
    """
    for key in list(_PENDING):
        wait_pending(key)
    for mgr in _MANAGERS.values():
        mgr.close()
    _MANAGERS.clear()


def reset_managers(abandon_pending: bool = False) -> None:
    """Drop every cached manager WITHOUT reusing it in the next world
    (resilience.membership, around an elastic reconfiguration).

    A cached CheckpointManager is bound to the world it was built in:
    its barrier decisions key off jax.process_count() at construction,
    and orbax's cross-host barrier names come from module-global
    counters that advance per operation. Carrying either across a
    membership epoch desynchronizes incumbents from fresh joiners (one
    side skips a barrier the other waits on — a deadlock, not an
    error). So at every reconfiguration: close or abandon the cached
    managers, then rewind orbax's barrier-name counters to match a
    fresh process.

    abandon_pending=True is the shrink path (a peer is DEAD, so any
    barrier — mgr.close, even waiting politely on an in-flight flush
    whose commit barriers against the dead host — can hang): pending
    flushes are dropped unwaited, the executor is discarded with its
    queue, and managers are unreferenced without close(). The flush
    thread may still be blocked inside orbax; it is a daemon-grade
    zombie whose step, if it ever commits, is pruned by the membership
    runtime (verify.prune_steps_above) before the new epoch's first
    save.
    """
    global _EXECUTOR
    if not abandon_pending:
        close_managers()
        _reset_orbax_barrier_counters()
        return
    with _LOCK:
        _PENDING.clear()
    if _EXECUTOR is not None:
        _EXECUTOR.shutdown(wait=False, cancel_futures=True)
        _EXECUTOR = None
    _MANAGERS.clear()
    _reset_orbax_barrier_counters()


def _reset_orbax_barrier_counters() -> None:
    """Rewind orbax's module-global barrier-name counters to zero.

    orbax.checkpoint.multihost.counters derives cross-host barrier key
    suffixes from itertools.count() module globals. After an elastic
    grow, an incumbent's counters have advanced past a fresh joiner's
    zeros, so their barrier names never match and both sides hang.
    Resetting every counter (on every member, incumbents and joiners
    alike — the reconfiguration round is the synchronization point)
    restores the alignment a fresh process pair would have."""
    import itertools

    try:
        from orbax.checkpoint.multihost import counters as _counters
    except Exception:
        return
    for name in dir(_counters):
        if isinstance(getattr(_counters, name), itertools.count):
            setattr(_counters, name, itertools.count())


# --- typed-PRNG-key leaf handler -----------------------------------------

def _is_typed_key(leaf: Any) -> bool:
    return jnp.issubdtype(getattr(leaf, "dtype", np.dtype(object)),
                          jax.dtypes.prng_key)


def _keys_to_data(tree: Any) -> Any:
    """Replace typed PRNG-key leaves with their uint32 key data (the only
    form orbax can serialize); old-style uint32 keys pass through."""
    return jax.tree.map(
        lambda x: jax.random.key_data(x) if _is_typed_key(x) else x, tree)


def _data_to_keys(tree: Any, template: Any) -> Any:
    """Re-wrap restored key data wherever the TEMPLATE leaf is a typed
    key, preserving the template's impl (threefry2x32 etc.) — the
    dtype-preserving half of the handler."""
    return jax.tree.map(
        lambda t, x: (jax.random.wrap_key_data(
            jnp.asarray(x, jnp.uint32), impl=jax.random.key_impl(t))
            if _is_typed_key(t) else x),
        template, tree)


# --- async save machinery -------------------------------------------------

def _host_snapshot(tree: Any) -> Any:
    """Donation-safe snapshot of every leaf, taken on the caller's
    thread before the flush is handed off.

    Replicated / host / numpy leaves snapshot as before: one device_get
    to a numpy copy, so the background flush on them is pure host I/O.

    SHARDED leaves (the live fsdp axis, parallel/layout.state_sharding
    — including cross-host shards, which have no full local copy to
    device_get) snapshot as an on-device copy instead: a distinct
    buffer the donated train step cannot invalidate, still in the
    leaf's sharding. The background flush hands it to orbax's
    sharding-aware serializer, which writes only each host's
    addressable shards (the per-shard path) under the same atomic
    commit — so a pod-scale fsdp save costs 1/N of the array per
    device, never a full gather."""
    def snap(x: Any) -> Any:
        if isinstance(x, jax.Array) and not x.is_fully_replicated:
            # jnp.copy follows the operand's sharding: a real per-shard
            # device-side copy, no cross-device traffic
            return jnp.copy(x)
        return jax.device_get(x)

    return jax.tree.map(snap, tree)

def _flush(key: str, step: int, host_state: Any, t0: float) -> float:
    """Background flush body: serialize + commit one step. Returns the
    flush duration. Runs on the single ckpt-flush thread; the manager is
    not touched by the foreground while this runs (barrier discipline)."""
    hold = flush_hold
    if hold is not None:
        # test-only chaos hook: the test that set it owns the release;
        # a timeout would end the staged zombie-flush scenario early
        hold.wait()  # jaxlint: disable=JL032 chaos hook, test-released
    mgr = _MANAGERS[key]
    mgr.save(step, args=ocp.args.StandardSave(host_state))
    # orbax's API has no timeout parameter; its internal commit barrier
    # is the only indefinite wait and multiprocess runs cap it via
    # patch_orbax_kv_barriers
    mgr.wait_until_finished()  # jaxlint: disable=JL032 no orbax timeout param
    return time.perf_counter() - t0


def save_checkpoint(directory: str, state: TrainState,
                    step: Optional[int] = None, *, block: bool = True) -> None:
    """Write <directory>/<step>/ with the full state.

    block=True (default) keeps the historical synchronous contract.
    block=False returns as soon as the state is snapshotted to host: the
    flush overlaps training and is committed at the next wait_pending
    barrier (or atexit). One flush per directory may be in flight — a
    second save first waits out the previous one.
    """
    key = os.path.abspath(directory)
    wait_pending(directory)
    _manager(directory, refresh=False)
    s = int(jax.device_get(state.step)) if step is None else int(step)
    # snapshot NOW, on the caller's thread: the donated train step may
    # invalidate these device buffers one step later. Replicated leaves
    # D2H here (inside the caller's transfer_guard("allow") window);
    # fsdp-sharded leaves stay on device as defensive copies and orbax
    # serializes them per-addressable-shard on the flush thread (whose
    # D2H is invisible to the main thread's strict transfer guard —
    # guard state is thread-local)
    host_state = _host_snapshot(_keys_to_data(state))
    t0 = time.perf_counter()
    started = threading.Event()

    def run() -> float:
        started.set()
        return _flush(key, s, host_state, t0)

    future = _executor().submit(run)
    with _LOCK:
        _PENDING[key] = {"step": s, "t0": t0, "future": future,
                         "started": started}
    if _chaos_kill_next_flush:
        # mid-flush crash injection: die once the flush is provably
        # MID-SERIALIZE — the orbax tmp dir exists (uncommitted
        # debris a real crash leaves) and the commit rename has not
        # happened. os._exit skips atexit, so the pending flush is
        # abandoned exactly as a SIGKILL would.
        started.wait(timeout=30)
        deadline = time.perf_counter() + 10
        observed_mid_flush = False
        while time.perf_counter() < deadline:
            try:
                names = os.listdir(key)
            except OSError:
                names = []
            if any(n.startswith(f"{s}.") and "orbax-checkpoint-tmp" in n
                   for n in names):
                observed_mid_flush = True
                break
            if str(s) in names:  # the flush won the race and committed
                break
            time.sleep(0.002)
        if not observed_mid_flush:
            # never caught the window (commit raced us, or the flush
            # errored before creating its tmp dir): exit DIFFERENTLY so
            # the chaos phase fails with the true cause instead of a
            # misleading 'mid-flush' claim
            print(f"[chaos] kill_mid_flush of step {s}: flush window "
                  f"never observed (already committed or failed); "
                  f"exiting 8, not 7", flush=True)
            os._exit(8)
        print(f"[chaos] killing process mid-flush of step {s}", flush=True)
        os._exit(7)
    if block:
        # the blocking contract is the historical one: a failed save
        # RAISES at the call site, so the caller never advances its
        # last-saved bookkeeping past a step that was never committed
        wait_pending(directory, raise_on_error=True)


def wait_pending(directory: Optional[str] = None,
                 raise_on_error: bool = False) -> Optional[Dict[str, Any]]:
    """Barrier: block until the directory's in-flight flush commits.

    Returns None when nothing was pending, else a stats dict
    {step, blocked_s, flush_s, error} — blocked_s is how long THIS call
    waited (the step loop's real cost), flush_s how long the flush took
    end to end (the overlapped work). A failed flush is reported loudly
    and recorded; by default it is NOT raised — the caller's next
    restore falls back to the previous committed step
    (resilience.verify), which is the recovery path a crashed flush
    needs anyway. raise_on_error=True re-raises it after the
    accounting (the blocking-save contract).
    """
    if directory is None:
        info = None
        for key in list(_PENDING):
            info = wait_pending(key, raise_on_error=raise_on_error) or info
        return info
    key = os.path.abspath(directory)
    with _LOCK:
        pending = _PENDING.pop(key, None)
    if pending is None:
        return None
    t_wait = time.perf_counter()
    error: Optional[str] = None
    exc: Optional[BaseException] = None
    flush_s = 0.0
    try:
        # transitively bounded: the flush body's only indefinite wait is
        # the orbax commit barrier (capped in multiprocess runs). An
        # expiring result() would NOT cancel the flush — it would only
        # let the foreground touch the manager mid-flush, breaking the
        # barrier discipline this module is built on
        flush_s = pending["future"].result()  # jaxlint: disable=JL032 barrier-bounded
    except Exception as e:  # orbax raises many types; the flush is lost
        exc = e
        error = f"{type(e).__name__}: {e}"
        flush_s = time.perf_counter() - pending["t0"]
        print(f"[checkpoint] async save of step {pending['step']} under "
              f"{directory} FAILED ({error}); the previous committed step "
              f"remains the latest", flush=True)
    blocked_s = time.perf_counter() - t_wait
    info = {"step": pending["step"], "blocked_s": blocked_s,
            "flush_s": flush_s, "error": error}
    with _LOCK:
        stats = _STATS.setdefault(key, {"saves": 0, "failed": 0,
                                        "total_blocked_s": 0.0,
                                        "total_flush_s": 0.0})
        stats["saves"] += 1
        stats["failed"] += 1 if error else 0
        stats["total_blocked_s"] += blocked_s
        stats["total_flush_s"] += flush_s
        stats["last"] = info
    if exc is not None and raise_on_error:
        raise exc
    return info


def pending_step(directory: str) -> Optional[int]:
    """Step number of the directory's in-flight flush, or None."""
    entry = _PENDING.get(os.path.abspath(directory))
    return None if entry is None else entry["step"]


def save_stats(directory: str) -> Dict[str, Any]:
    """Cumulative async-save accounting for the directory: saves, failed,
    total_blocked_s, total_flush_s, last {step, blocked_s, flush_s}."""
    return dict(_STATS.get(os.path.abspath(directory), {}))


def latest_step(directory: str) -> Optional[int]:
    wait_pending(directory)
    return _manager(directory).latest_step()


def all_steps(directory: str) -> "list[int]":
    """Ascending list of saved steps."""
    wait_pending(directory)
    return sorted(int(s) for s in _manager(directory).all_steps())


def delete_step(directory: str, step: int) -> None:
    """Remove one saved step (retention GC). Falls back to an rmtree of
    the step dir when the manager refuses (e.g. a half-written step the
    manager no longer tracks) — naming what failed and why, so retention
    GC failures surface in the run log instead of vanishing."""
    wait_pending(directory)
    mgr = _manager(directory, refresh=False)
    step_dir = os.path.join(directory, str(int(step)))
    try:
        mgr.delete(int(step))
    except Exception as e:
        print(f"[checkpoint] manager delete of step {step} under "
              f"{directory} failed ({type(e).__name__}: {e}); removing "
              f"{step_dir} directly", flush=True)
        import shutil

        shutil.rmtree(step_dir, ignore_errors=True)
        if os.path.isdir(step_dir):
            print(f"[checkpoint] rmtree fallback also left {step_dir} "
                  f"behind — retention GC is NOT reclaiming this step",
                  flush=True)
        if hasattr(mgr, "reload"):
            mgr.reload()


def _fs_steps(directory: str) -> "list[int]":
    """Step dirs found by a plain filesystem walk — no CheckpointManager,
    so probing a path NEVER creates it (the cached managers are built
    with create=True, which would turn every probe into a mkdir).
    Uncommitted flushes (``<step>.orbax-checkpoint-tmp-*``) are not
    digits, so a crash mid-flush never lists its half-written step."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(int(n) for n in names
                  if n.isdigit() and os.path.isdir(os.path.join(directory, n)))


def require_checkpoints(directory: str) -> None:
    """One-line actionable error for a missing or empty checkpoint dir.

    The orbax path for this failure is a multi-screen traceback ending in
    an internal FileNotFoundError; here the operator gets the offending
    path plus the nearest sibling dirs that DO hold checkpoints (the
    usual failure is a typo'd or stale experiment name).
    """
    if _fs_steps(directory):
        return
    parent = os.path.dirname(os.path.abspath(directory)) or "."
    try:
        siblings = sorted(n for n in os.listdir(parent)
                          if _fs_steps(os.path.join(parent, n)))
    except OSError:
        siblings = []
    detail = ("directory does not exist"
              if not os.path.isdir(directory) else "no saved steps in it")
    hint = (f"; checkpoint dirs under {parent!r}: {', '.join(siblings[:8])}"
            if siblings else f"; no checkpoint dirs under {parent!r} either")
    raise FileNotFoundError(
        f"no checkpoints under {directory!r} ({detail}){hint}")


def _abstract_leaf(x: Any) -> Any:
    """ShapeDtypeStruct for a template leaf, carrying the leaf's mesh
    sharding when it has one: orbax then restores straight INTO that
    layout — each host reads only its shards (the per-shard restore
    path the fsdp axis needs; works equally for resharding a
    replicated-era checkpoint onto an fsdp mesh and vice versa).
    Host/numpy and single-device template leaves keep the historical
    plain-abstract restore."""
    sharding = getattr(x, "sharding", None)
    if isinstance(sharding, jax.sharding.NamedSharding):
        return jax.ShapeDtypeStruct(np.shape(x), x.dtype, sharding=sharding)
    return ocp.utils.to_shape_dtype_struct(x)


def restore_checkpoint(
    directory: str, template: TrainState, step: Optional[int] = None
) -> TrainState:
    """Restore a full TrainState; ``template`` supplies tree structure,
    shapes, and shardings (create one with create_state; shard it with
    parallel.layout.shard_state to land the restore sharded). Typed
    PRNG-key leaves in the template are restored dtype-preserving
    (re-wrapped from their saved key data with the template's impl)."""
    wait_pending(directory)
    mgr = _manager(directory)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    data_template = _keys_to_data(template)
    abstract = jax.tree.map(_abstract_leaf, data_template)
    restored = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    return _data_to_keys(restored, template)


def restore_raw(directory: str, step: Optional[int] = None) -> Any:
    """Template-free restore of the raw saved tree (numpy leaves).

    The inference-only consumers (dexined test mode) have no TrainState
    template; this goes through the SAME cached-manager path as every
    other restore — a fresh ad-hoc CheckpointManager cannot infer the
    saved item's handler (orbax KeyError: 'Item \"default\" … could not
    be restored') and would race a cached manager's pending flush."""
    wait_pending(directory)
    mgr = _manager(directory)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    return mgr.restore(step, args=ocp.args.StandardRestore())


def restore_params_into(
    params: Any, restored_params: Any, verbose: bool = False,
    skipped_report_dir: Optional[str] = None,
) -> Tuple[Any, list]:
    """strict=False load: graft every leaf whose path exists in both trees
    with matching shape; keep the fresh init elsewhere. Returns (merged,
    list of skipped/missing path strings).

    verbose prints the first 8 skipped paths inline WITH the total; when
    more were skipped the full list goes to a sidecar file
    (<skipped_report_dir>/partial_restore_skipped.txt, cwd if not given)
    so an architecture-drift load is auditable leaf by leaf instead of
    ending in an ellipsis."""
    flat_new = {jax.tree_util.keystr(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(params)[0]}
    flat_old = {jax.tree_util.keystr(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(restored_params)[0]}

    skipped = []
    merged = dict(flat_new)
    for key, new_leaf in flat_new.items():
        old = flat_old.get(key)
        if old is not None and tuple(old.shape) == tuple(new_leaf.shape):
            # graft into the template leaf's RESOLVED sharding: on an
            # fsdp mesh the fresh init is already in its storage layout
            # (layout.shard_state), and a restored leaf — whatever mesh
            # or era saved it — must land the same way, not as a
            # host-local replicated copy that the first fenced step
            # would then silently reshard
            sharding = getattr(new_leaf, "sharding", None)
            if (isinstance(sharding, jax.sharding.NamedSharding)
                    and not getattr(old, "sharding", None) == sharding):
                old = jax.device_put(old, sharding)
            merged[key] = old
        else:
            skipped.append(key)
    skipped += [k for k in flat_old if k not in flat_new]
    if verbose and skipped:
        inline_cap = 8
        tail = ""
        if len(skipped) > inline_cap:
            report = os.path.join(skipped_report_dir or ".",
                                  "partial_restore_skipped.txt")
            try:
                os.makedirs(os.path.dirname(report) or ".", exist_ok=True)
                with open(report, "w") as f:
                    f.write("\n".join(skipped) + "\n")
                tail = f"; full list -> {report}"
            except OSError as e:
                tail = f"; (could not write full list: {e})"
        print(f"[checkpoint] partial restore skipped {len(skipped)} leaves "
              f"(first {min(inline_cap, len(skipped))} of {len(skipped)}): "
              f"{skipped[:inline_cap]}{tail}")

    # rebuild the tree: map leaves back by path order
    paths, treedef = jax.tree_util.tree_flatten_with_path(params)
    leaves = [merged[jax.tree_util.keystr(kp)] for kp, _ in paths]
    return jax.tree_util.tree_unflatten(treedef, leaves), skipped
