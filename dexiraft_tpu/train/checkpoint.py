"""Orbax checkpointing: full train state + partial (curriculum) restore.

Upgrades the reference's torch.save(model.state_dict()) every 5k steps
(train.py:189-190): here params, BatchNorm stats, optimizer state, step,
and PRNG key all round-trip, so resume continues the OneCycle schedule
instead of restarting it (the reference's documented gap, SURVEY.md §5).

``restore_params_into`` reproduces load_state_dict(strict=False)
(train.py:143-144): stage-to-stage and architecture-drift loads keep every
leaf whose path and shape match and leave the rest freshly initialized.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from dexiraft_tpu.train.state import TrainState


def _manager(directory: str, max_to_keep: Optional[int] = None) -> ocp.CheckpointManager:
    return ocp.CheckpointManager(
        os.path.abspath(directory),
        options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
    )


def save_checkpoint(directory: str, state: TrainState, step: Optional[int] = None) -> None:
    """Write <directory>/<step>/ with the full state (blocking)."""
    mgr = _manager(directory)
    s = int(state.step) if step is None else int(step)
    mgr.save(s, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()
    mgr.close()


def latest_step(directory: str) -> Optional[int]:
    mgr = _manager(directory)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore_checkpoint(
    directory: str, template: TrainState, step: Optional[int] = None
) -> TrainState:
    """Restore a full TrainState; ``template`` supplies tree structure,
    shapes, and shardings (create one with create_state)."""
    mgr = _manager(directory)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    restored = mgr.restore(step, args=ocp.args.StandardRestore(abstract))
    mgr.close()
    return restored


def restore_params_into(
    params: Any, restored_params: Any, verbose: bool = False
) -> Tuple[Any, list]:
    """strict=False load: graft every leaf whose path exists in both trees
    with matching shape; keep the fresh init elsewhere. Returns (merged,
    list of skipped/missing path strings)."""
    flat_new = {jax.tree_util.keystr(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(params)[0]}
    flat_old = {jax.tree_util.keystr(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(restored_params)[0]}

    skipped = []
    merged = dict(flat_new)
    for key, new_leaf in flat_new.items():
        old = flat_old.get(key)
        if old is not None and tuple(old.shape) == tuple(new_leaf.shape):
            merged[key] = old
        else:
            skipped.append(key)
    skipped += [k for k in flat_old if k not in flat_new]
    if verbose and skipped:
        print(f"[checkpoint] partial restore skipped {len(skipped)} leaves: {skipped[:8]}…")

    # rebuild the tree: map leaves back by path order
    paths, treedef = jax.tree_util.tree_flatten_with_path(params)
    leaves = [merged[jax.tree_util.keystr(kp)] for kp, _ in paths]
    return jax.tree_util.tree_unflatten(treedef, leaves), skipped
