"""Orbax checkpointing: full train state + partial (curriculum) restore.

Upgrades the reference's torch.save(model.state_dict()) every 5k steps
(train.py:189-190): here params, BatchNorm stats, optimizer state, step,
and PRNG key all round-trip, so resume continues the OneCycle schedule
instead of restarting it (the reference's documented gap, SURVEY.md §5).

``restore_params_into`` reproduces load_state_dict(strict=False)
(train.py:143-144): stage-to-stage and architecture-drift loads keep every
leaf whose path and shape match and leave the rest freshly initialized.
"""

from __future__ import annotations

import atexit
import os
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from dexiraft_tpu.train.state import TrainState


_MANAGERS: "dict[str, ocp.CheckpointManager]" = {}


def _manager(directory: str, refresh: bool = True) -> ocp.CheckpointManager:
    """One live CheckpointManager per directory.

    Constructing and closing a fresh manager per save/restore is fine at
    VAL_FREQ=5000 but wasteful the moment the cadence tightens (each
    construction lists the directory and spins up orbax's async save
    machinery). Cached managers are reload()ed before READS so steps
    written by another process are still observed; writers pass
    refresh=False (a save needs no directory re-listing).
    """
    key = os.path.abspath(directory)
    mgr = _MANAGERS.get(key)
    if mgr is None:
        mgr = ocp.CheckpointManager(
            key, options=ocp.CheckpointManagerOptions(create=True))
        _MANAGERS[key] = mgr
    elif refresh and hasattr(mgr, "reload"):
        mgr.reload()
    return mgr


@atexit.register
def close_managers() -> None:
    """Close every cached manager (flushes pending async work).

    Registered atexit so long processes touching many directories (a
    pytest run's tmp dirs) don't leak orbax's per-manager machinery
    through interpreter shutdown; safe to call earlier by hand.
    """
    for mgr in _MANAGERS.values():
        mgr.close()
    _MANAGERS.clear()


def save_checkpoint(directory: str, state: TrainState, step: Optional[int] = None) -> None:
    """Write <directory>/<step>/ with the full state (blocking)."""
    mgr = _manager(directory, refresh=False)
    s = int(state.step) if step is None else int(step)
    mgr.save(s, args=ocp.args.StandardSave(state))
    mgr.wait_until_finished()


def latest_step(directory: str) -> Optional[int]:
    return _manager(directory).latest_step()


def all_steps(directory: str) -> "list[int]":
    """Ascending list of saved steps."""
    return sorted(int(s) for s in _manager(directory).all_steps())


def delete_step(directory: str, step: int) -> None:
    """Remove one saved step (retention GC). Falls back to an rmtree of
    the step dir when the manager refuses (e.g. a half-written step the
    manager no longer tracks)."""
    mgr = _manager(directory, refresh=False)
    try:
        mgr.delete(int(step))
    except Exception:
        import shutil

        shutil.rmtree(os.path.join(directory, str(int(step))),
                      ignore_errors=True)
        if hasattr(mgr, "reload"):
            mgr.reload()


def _fs_steps(directory: str) -> "list[int]":
    """Step dirs found by a plain filesystem walk — no CheckpointManager,
    so probing a path NEVER creates it (the cached managers are built
    with create=True, which would turn every probe into a mkdir)."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(int(n) for n in names
                  if n.isdigit() and os.path.isdir(os.path.join(directory, n)))


def require_checkpoints(directory: str) -> None:
    """One-line actionable error for a missing or empty checkpoint dir.

    The orbax path for this failure is a multi-screen traceback ending in
    an internal FileNotFoundError; here the operator gets the offending
    path plus the nearest sibling dirs that DO hold checkpoints (the
    usual failure is a typo'd or stale experiment name).
    """
    if _fs_steps(directory):
        return
    parent = os.path.dirname(os.path.abspath(directory)) or "."
    try:
        siblings = sorted(n for n in os.listdir(parent)
                          if _fs_steps(os.path.join(parent, n)))
    except OSError:
        siblings = []
    detail = ("directory does not exist"
              if not os.path.isdir(directory) else "no saved steps in it")
    hint = (f"; checkpoint dirs under {parent!r}: {', '.join(siblings[:8])}"
            if siblings else f"; no checkpoint dirs under {parent!r} either")
    raise FileNotFoundError(
        f"no checkpoints under {directory!r} ({detail}){hint}")


def restore_checkpoint(
    directory: str, template: TrainState, step: Optional[int] = None
) -> TrainState:
    """Restore a full TrainState; ``template`` supplies tree structure,
    shapes, and shardings (create one with create_state)."""
    mgr = _manager(directory)
    if step is None:
        step = mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    return mgr.restore(step, args=ocp.args.StandardRestore(abstract))


def restore_params_into(
    params: Any, restored_params: Any, verbose: bool = False
) -> Tuple[Any, list]:
    """strict=False load: graft every leaf whose path exists in both trees
    with matching shape; keep the fresh init elsewhere. Returns (merged,
    list of skipped/missing path strings)."""
    flat_new = {jax.tree_util.keystr(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(params)[0]}
    flat_old = {jax.tree_util.keystr(kp): v
                for kp, v in jax.tree_util.tree_flatten_with_path(restored_params)[0]}

    skipped = []
    merged = dict(flat_new)
    for key, new_leaf in flat_new.items():
        old = flat_old.get(key)
        if old is not None and tuple(old.shape) == tuple(new_leaf.shape):
            merged[key] = old
        else:
            skipped.append(key)
    skipped += [k for k in flat_old if k not in flat_new]
    if verbose and skipped:
        print(f"[checkpoint] partial restore skipped {len(skipped)} leaves: {skipped[:8]}…")

    # rebuild the tree: map leaves back by path order
    paths, treedef = jax.tree_util.tree_flatten_with_path(params)
    leaves = [merged[jax.tree_util.keystr(kp)] for kp, _ in paths]
    return jax.tree_util.tree_unflatten(treedef, leaves), skipped
