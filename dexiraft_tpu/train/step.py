"""Jitted train / eval steps, single-chip or sharded over a device mesh.

The reference's inner loop (train.py:163-186: forward, sequence loss,
backward, unscale/clip/step, scheduler) becomes ONE jitted function —
the 12-iteration refinement loop, loss, and optimizer update all compile
into a single on-device graph. Data parallelism is declarative: the batch
is sharded over the mesh's 'data' axis, the state is replicated, and the
SPMD partitioner inserts the gradient all-reduce over ICI (the TPU-native
replacement for DataParallel's NCCL gather, SURVEY.md §2.7).

On an fsdp mesh (parallel/layout.make_train_mesh(..., fsdp=...)) the
state is additionally STORED sharded: params and Adam moments live
split over the 'fsdp' axis between steps (per-leaf layout in
layout.state_sharding). How the COMPUTE relates to that storage is the
``compute_sharding`` axis:

  * "fence" (default) — the step gathers the state to replicated at
    entry and re-shards at exit (the fence pattern, docs/perf.md
    "Sharded state (fsdp)"). Compute inside the fences is byte-for-byte
    the replicated program; what changes is the persistent per-device
    HBM (state at ~1/fsdp) and the checkpoint path (per-shard orbax
    I/O). Works for every variant/config.
  * "halo" — the heavy spatial compute itself shards: a shard_map over
    the mesh's (data, seq) axes gives each device a contiguous
    image-row slab, convolutions exchange receptive-field boundary rows
    with lax.ppermute (parallel/halo.py), and params stay fsdp-sharded
    THROUGH compute — each block all-gathers its weights immediately
    before running and drops them after (gather->use->drop inside
    jax.checkpoint), so peak gathered-params HBM is one block. The
    optimizer update runs OUTSIDE the shard_map on the sharded grads
    (elementwise; GSPMD partitions it over fsdp for free), so no
    fences exist anywhere in this mode. v1/fp32-only support matrix:
    halo.check_halo_support refuses everything else with actionable
    errors.

BatchNorm note: under a sharded batch the normalizing statistics are
GLOBAL across chips (XLA inserts the cross-chip mean) — i.e. sync-BN.
The reference's DataParallel computes per-device stats; sync-BN is the
strictly better-behaved variant, so we adopt it deliberately.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from dexiraft_tpu.config import RAFTConfig, TrainConfig
from dexiraft_tpu.models.raft import RAFT
from dexiraft_tpu.ops.losses import sequence_loss
from dexiraft_tpu.parallel import halo
from dexiraft_tpu.parallel.layout import (
    LAYOUT,
    batch_input_sharding,
    batch_sharding,
    replicated_sharding,
    state_sharding,
    variables_sharding,
)
from dexiraft_tpu.train.optimizer import training_schedule
from dexiraft_tpu.train.state import TrainState, create_state, make_optimizer_from

Batch = Dict[str, jax.Array]  # image1, image2, flow, valid [, edges1, edges2]


def all_finite(*trees: Any) -> jax.Array:
    """Scalar bool: every inexact leaf of every tree is finite.

    The checkpoint gate's poison detector. The guard's loss check alone
    has a one-step blind spot: value_and_grad computes the loss from the
    PRE-update params, but the checkpoint saves the POST-update state —
    a step whose update introduces non-finite values passes the loss
    check and the poisoned state reaches disk. Emitting this signal from
    the step itself (computed on the NEW state) closes that gap; it is
    one elementwise pass over the state, noise next to the backward.
    """
    ok = jnp.bool_(True)
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            leaf = jnp.asarray(leaf)
            if jnp.issubdtype(leaf.dtype, jnp.inexact):
                ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def _add_noise(rng: jax.Array, stdv: jax.Array, image: jax.Array) -> jax.Array:
    """Gaussian noise at the given stdv, clipped to [0,255] (train.py:170-173);
    the reference draws ONE stdv ~ U(0,5) shared by both frames."""
    noisy = image + stdv * jax.random.normal(rng, image.shape, jnp.float32)
    return jnp.clip(noisy, 0.0, 255.0)


def cast_floating(tree: Any, dtype: Any) -> Any:
    """Cast every floating leaf of a pytree to dtype; leave the rest alone."""
    def cast(x):
        x = jnp.asarray(x)
        return x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x
    return jax.tree.map(cast, tree)


def make_train_step(
    cfg: RAFTConfig,
    tc: TrainConfig,
    mesh: Optional[Mesh] = None,
    compute_sharding: str = "fence",
) -> Callable[[TrainState, Batch], Tuple[TrainState, Dict[str, jax.Array]]]:
    """Build the jitted train step. With a mesh, in/out shardings pin the
    batch to the 'data' axis (rows additionally over 'seq' on 2-D
    meshes) and everything else replicated/fsdp-stored.
    ``compute_sharding`` picks how fsdp storage meets compute: "fence"
    gathers at entry / re-shards at exit; "halo" shard_maps the spatial
    compute with explicit halo exchange and keeps params sharded
    throughout (module docstring has the full contrast)."""
    if tc.precision not in ("fp32", "bf16"):
        raise ValueError(f"precision must be fp32|bf16, got {tc.precision!r}")
    if tc.accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {tc.accum_steps}")
    if compute_sharding not in ("fence", "halo"):
        raise ValueError(f"compute_sharding must be fence|halo, "
                         f"got {compute_sharding!r}")
    if tc.remat not in ("none", "per_iter", "dots_saveable"):
        raise ValueError(f"remat must be none|per_iter|dots_saveable, "
                         f"got {tc.remat!r}")
    if tc.remat != "none":
        import dataclasses

        # thread the TrainConfig remat axis into the model config: both
        # checkpointing modes wrap the scanned iteration; the policy
        # decides what the checkpoint saves (config.py remat_policy)
        cfg = dataclasses.replace(
            cfg, remat=True,
            remat_policy=("dots_saveable" if tc.remat == "dots_saveable"
                          else "full"))
    if compute_sharding == "halo":
        return _make_halo_train_step(cfg, tc, mesh)
    # bf16 training policy: force the MODEL's mixed-precision path —
    # module compute dtype becomes bf16, so flax casts each op's params
    # from the fp32 masters per use (autodiff transposes the casts and
    # the gradients land back fp32), activations are genuinely bf16, and
    # the corr volume stays fp32 by the model's own mixed-precision
    # contract. Everything after the model — loss, metrics, BN running
    # stats, optimizer — stays fp32. No loss scaling: bf16 shares fp32's
    # exponent range (README design note). NOTE a hand-cast of params /
    # inputs here would NOT work: RAFT.__call__ re-casts inputs fp32 and
    # derives its compute dtype from cfg.mixed_precision alone.
    bf16 = tc.precision == "bf16"
    if bf16 and not cfg.mixed_precision:
        import dataclasses

        cfg = dataclasses.replace(cfg, mixed_precision=True)
    model = RAFT(cfg)
    if tc.edge_sum_fusion and (cfg.variant != "raft" or cfg.embed_dexined):
        raise ValueError(
            "edge_sum_fusion is the v1 (plain 'raft') training fusion — "
            "the model itself consumes edges in the other variants")
    tx = make_optimizer_from(tc)
    schedule = training_schedule(tc.lr, tc.num_steps)

    def loss_fn(params: Any, batch_stats: Any, batch: Batch, rng: jax.Array):
        def fwd(stats, drop_rng, im1, im2, **kw):
            return model.apply(
                {"params": params, "batch_stats": stats},
                im1, im2, iters=tc.iters, train=True,
                freeze_bn=tc.freeze_bn, mutable=["batch_stats"],
                rngs={"dropout": drop_rng}, **kw,
            )

        if tc.edge_sum_fusion:
            if "edges1" not in batch:
                raise ValueError("edge_sum_fusion needs edge-pair data "
                                 "(edge_root)")
            # v1-lineage summed fusion (alt/train_1.py:173-176): same
            # model on the image pair and the edge-image pair, per-iter
            # predictions summed; BN stats update through both passes
            # sequentially, and each pass draws independent dropout masks
            # like the reference's two separate forward calls
            rng_img, rng_edge = jax.random.split(rng)
            img_flow, mut1 = fwd(batch_stats, rng_img,
                                 batch["image1"], batch["image2"])
            edge_flow, mut2 = fwd(mut1.get("batch_stats", batch_stats),
                                  rng_edge,
                                  batch["edges1"], batch["edges2"])
            outputs = img_flow + edge_flow
            mutated = mut2
        else:
            kwargs: Dict[str, Any] = {}
            if "edges1" in batch:
                kwargs = dict(edges1=batch["edges1"], edges2=batch["edges2"])
            outputs, mutated = fwd(batch_stats, rng, batch["image1"],
                                   batch["image2"], **kwargs)
        new_stats = mutated.get("batch_stats", batch_stats)
        if bf16:
            # fp32 loss/metrics and fp32 carried state, whatever dtype
            # the bf16 forward emitted
            outputs = outputs.astype(jnp.float32)
            new_stats = cast_floating(new_stats, jnp.float32)
        loss, metrics = sequence_loss(outputs, batch["flow"], batch["valid"], tc.gamma)
        return loss, (metrics, new_stats)

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    # fsdp fence shardings, filled in below when the mesh has the axis;
    # None on every other path so the step body compiles unchanged
    fence_repl = None

    def step(state: TrainState, batch: Batch):
        if fence_repl is not None:
            # ENTRY FENCE (fsdp): the state arrives in its storage
            # layout (params/opt_state sharded over 'fsdp' per
            # layout.state_sharding); gather it to replicated HERE so
            # the partitioner never sees an fsdp-sharded tensor inside
            # the model — GSPMD miscompiles feature-dim-partitioned
            # convolutions on this backend (the conv-of-concat repro in
            # tests/test_zzzfsdp.py), so fsdp is a storage axis only.
            # Everything below computes exactly the replicated program.
            state = jax.lax.with_sharding_constraint(state, fence_repl)
        rng, noise_rng, dropout_rng = jax.random.split(state.rng, 3)
        if tc.add_noise:
            k_stdv, k1, k2 = jax.random.split(noise_rng, 3)
            stdv = jax.random.uniform(k_stdv, (), jnp.float32, 0.0, 5.0)
            batch = dict(batch)
            batch["image1"] = _add_noise(k1, stdv, batch["image1"])
            batch["image2"] = _add_noise(k2, stdv, batch["image2"])

        accum = tc.accum_steps
        if accum > 1:
            # gradient accumulation: scan over microbatches INSIDE the
            # jitted step, so a large effective batch fits one chip and
            # the accumulation loop compiles once. The batch's leading
            # dim is (accum * micro); per-microbatch mean grads average
            # to exactly the full-batch mean grad FOR BN-FREE VARIANTS
            # (small RAFT — pinned by test). With BatchNorm in train
            # mode each microbatch normalizes over micro samples, not
            # the full batch (the usual accumulation caveat, same as
            # every framework's; equivalent to training at the smaller
            # BN batch). Running stats thread sequentially through the
            # scan carry, like sequential steps would
            b = batch["image1"].shape[0]
            if b % accum:
                raise ValueError(
                    f"batch {b} not divisible by accum_steps {accum}")
            if mesh is not None:
                # each microbatch must still split over the data axis,
                # or GSPMD reshards / idles chips on EVERY scan
                # iteration — the opposite of what accumulation buys
                n_data = LAYOUT.data_size(mesh)
                if (b // accum) % n_data:
                    raise ValueError(
                        f"microbatch {b // accum} (batch {b} / accum "
                        f"{accum}) not divisible by the mesh's "
                        f"{n_data}-way data axis — every chip must "
                        f"keep a full shard per scan iteration")
            micro = jax.tree.map(
                lambda x: x.reshape((accum, b // accum) + x.shape[1:]),
                batch)
            rngs = jax.random.split(dropout_rng, accum)

            def body(carry, xs):
                stats, acc = carry
                mb, r = xs
                (mb_loss, (mb_metrics, stats)), grads = grad_fn(
                    state.params, stats, mb, r)
                acc = jax.tree.map(jnp.add, acc, grads)
                return (stats, acc), (mb_loss, mb_metrics)

            zeros = jax.tree.map(jnp.zeros_like, state.params)
            (batch_stats, gsum), (losses, seq_metrics) = jax.lax.scan(
                body, (state.batch_stats, zeros), (micro, rngs))
            grads = jax.tree.map(lambda g: g / accum, gsum)
            loss = jnp.mean(losses)
            metrics = jax.tree.map(jnp.mean, seq_metrics)
        else:
            (loss, (metrics, batch_stats)), grads = grad_fn(
                state.params, state.batch_stats, batch, dropout_rng)

        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = jax.tree.map(lambda p, u: p + u, state.params, updates)

        new_state = TrainState(
            step=state.step + 1,
            params=params,
            batch_stats=batch_stats,
            opt_state=opt_state,
            rng=rng,
        )
        metrics = dict(metrics, loss=loss, lr=schedule(state.step),
                       state_finite=all_finite(params, batch_stats,
                                               opt_state))
        if fence_repl is not None:
            # EXIT FENCE (fsdp): pin the finished state replicated so
            # sharding propagation from the sharded out_shardings below
            # stops at this boundary — the re-shard back to storage
            # layout is a pure slice at the jit output, never a
            # different partitioning of the compute above.
            new_state = jax.lax.with_sharding_constraint(
                new_state, fence_repl)
        return new_state, metrics

    if mesh is None:
        return jax.jit(step, donate_argnums=0)

    repl = replicated_sharding(mesh)
    # 2-D (data, seq) mesh: image rows additionally shard over 'seq' —
    # GSPMD partitions the convs (halo exchange) and the correlation
    # volume's query axis (context parallelism); every batch leaf is >=3D
    # (B, H, ...), so one spec covers the dict. batch_input_sharding is
    # the same helper the device prefetcher puts with, so prefetched
    # batches arrive already in this layout
    data = batch_input_sharding(mesh)
    state_sh = repl
    if LAYOUT.has_fsdp(mesh):
        # fsdp mesh: pin the state's STORAGE layout per leaf — params
        # and Adam moments sharded over 'fsdp' (layout.param_leaf_spec
        # decides dim + divisibility fallback centrally), the rest
        # replicated. The step body gathers at entry and re-pins at
        # exit (fences above); in/out match, so donation still aliases
        # shard-for-shard. The abstract eval_shape costs one host-side
        # trace of create_state, only on fsdp meshes.
        abstract = jax.eval_shape(
            lambda: create_state(jax.random.PRNGKey(0), cfg, tc))
        state_sh = state_sharding(mesh, abstract)
        fence_repl = jax.tree.map(lambda _: repl, abstract)
    return jax.jit(
        step,
        in_shardings=(state_sh, data),
        out_shardings=(state_sh, repl),
        donate_argnums=0,
    )


def _make_halo_train_step(
    cfg: RAFTConfig,
    tc: TrainConfig,
    mesh: Optional[Mesh],
) -> Callable[[TrainState, Batch], Tuple[TrainState, Dict[str, jax.Array]]]:
    """The compute_sharding="halo" train step (make_train_step
    dispatches here): the shard_map'd gradient fn from
    parallel/halo.py plus the optimizer update OUTSIDE the shard_map.

    Grads leave the shard_map already in the params' fsdp storage
    layout, so the Adam update (elementwise per leaf; the global-norm
    clip reduces over shards, which GSPMD handles) never materializes a
    replicated param tree — persistent AND peak optimizer HBM stay at
    ~1/fsdp. batch_stats pass through unchanged: halo trains with
    instance norm / frozen BN only (check_halo_support), so there are
    no running-stat updates to thread. The rng splits once per step to
    keep the TrainState contract (fresh carry each step) even though
    the halo forward draws no randomness (dropout/noise refused)."""
    halo.check_halo_support(cfg, tc, mesh)
    tx = make_optimizer_from(tc)
    schedule = training_schedule(tc.lr, tc.num_steps)
    abstract = jax.eval_shape(
        lambda: create_state(jax.random.PRNGKey(0), cfg, tc))
    halo_fn = halo.make_halo_train_fn(cfg, tc, mesh, abstract.params,
                                      remat_mode=tc.remat)
    state_sh = state_sharding(mesh, abstract)
    repl = replicated_sharding(mesh)
    data = batch_input_sharding(mesh)  # P('data', 'seq') on seq meshes

    def step(state: TrainState, batch: Batch):
        rng, _ = jax.random.split(state.rng)
        loss, metrics, grads = halo_fn(
            state.params, state.batch_stats, batch["image1"],
            batch["image2"], batch["flow"], batch["valid"])
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = jax.tree.map(lambda p, u: p + u, state.params, updates)
        new_state = TrainState(
            step=state.step + 1,
            params=params,
            batch_stats=state.batch_stats,
            opt_state=opt_state,
            rng=rng,
        )
        metrics = dict(metrics, loss=loss, lr=schedule(state.step),
                       state_finite=all_finite(params, state.batch_stats,
                                               opt_state))
        return new_state, metrics

    return jax.jit(
        step,
        in_shardings=(state_sh, data),
        out_shardings=(state_sh, repl),
        donate_argnums=0,
    )


def make_eval_step(
    cfg: RAFTConfig,
    iters: int = 24,
    mesh: Optional[Mesh] = None,
    compute_sharding: str = "fence",
    adaptive: bool = False,
) -> Callable[..., Tuple[jax.Array, ...]]:
    """Jitted test-mode forward: (flow_low, flow_up) like core/raft.py:194-197.

    Batched NHWC inputs throughout — the serving engine
    (dexiraft_tpu.serve) feeds bucket-padded batches straight in.
    flow_init enables warm-start inference (evaluate.py:40-44); a
    flow_init row of zeros equals no warm start (RAFT adds it to
    coords0), so one batch can carry PER-ITEM warm starts — warm rows
    next to cold zero rows — which is how the batched Sintel submission
    threads each sequence's carry through a shared batch.

    With a mesh the step pins its shardings like the train step does:
    batch args over the 'data' axis, variables replicated, outputs left
    sharded (the engine's per-item host fetch assembles them; no
    all-gather on device). Pinned shardings mean the mesh-path step must
    be called POSITIONALLY with all six arguments (jit rejects kwargs
    when in_shardings is set) — mesh=None keeps the kwarg-friendly
    reference behavior.

    ``compute_sharding="halo"`` swaps in the shard_map'd row-slab
    forward (parallel/halo.make_halo_eval_fn): image rows shard over
    the mesh's 'seq' axis and params stay in fsdp storage layout
    through compute. That step's signature differs — (variables,
    image1, image2, flow_init), positional, no edge arguments (v1
    only) and flow_init always materialized (zeros = cold start) —
    because its in_shardings pin the halo contract, not the engine's.

    ``adaptive=True`` swaps the fixed scan for the convergence-gated
    while_loop (RAFT adaptive=True): the step grows a trailing
    ``iter_budget`` positional — a TRACED int32 scalar, so ONE compiled
    executable per bucket serves every budget — and returns
    (flow_low, flow_up, iters_used[B], final_delta[B]). The (B,)
    outputs pin batch-only shardings on a mesh (they have no spatial
    dims for a seq axis to split).
    """
    if compute_sharding not in ("fence", "halo"):
        raise ValueError(f"compute_sharding must be fence|halo, "
                         f"got {compute_sharding!r}")
    model = RAFT(cfg)
    if compute_sharding == "halo":
        if adaptive:
            raise ValueError(
                "adaptive=True is not supported with "
                "compute_sharding='halo' (the shard_map'd row-slab "
                "forward drives the fixed-iteration halo loop)")
        return _make_halo_eval_step(cfg, iters, mesh, model)

    def step(
        variables: Dict[str, Any],
        image1: jax.Array,
        image2: jax.Array,
        edges1: Optional[jax.Array] = None,
        edges2: Optional[jax.Array] = None,
        flow_init: Optional[jax.Array] = None,
        iter_budget: Optional[jax.Array] = None,
    ):
        kwargs: Dict[str, Any] = {}
        if edges1 is not None:
            kwargs = dict(edges1=edges1, edges2=edges2)
        if adaptive:
            kwargs.update(adaptive=True, iter_budget=iter_budget)
        return model.apply(
            variables,
            image1,
            image2,
            iters=iters,
            flow_init=flow_init,
            train=False,
            test_mode=True,
            **kwargs,
        )

    if mesh is None:
        return jax.jit(step)
    repl = replicated_sharding(mesh)
    data = batch_input_sharding(mesh)
    vec = batch_sharding(mesh)  # (B,) outputs: batch axis only
    # one `data` leaf per batched positional (images, edges, flow_init);
    # a None optional consumes its sharding entry as an empty pytree.
    # The adaptive budget scalar replicates like every other scalar.
    if adaptive:
        return jax.jit(
            step,
            in_shardings=(repl, data, data, data, data, data, repl),
            out_shardings=(data, data, vec, vec),
        )
    return jax.jit(
        step,
        in_shardings=(repl, data, data, data, data, data),
        out_shardings=(data, data),
    )


def _make_halo_eval_step(
    cfg: RAFTConfig,
    iters: int,
    mesh: Optional[Mesh],
    model: RAFT,
) -> Callable[..., Tuple[jax.Array, jax.Array]]:
    """The compute_sharding="halo" eval step (make_eval_step dispatches
    here): (variables, image1, image2, flow_init) -> (flow_low,
    flow_up), all batch leaves row-sharded over (data, seq), variables
    pinned to their STORAGE layout (params per param_leaf_spec,
    batch_stats replicated — layout.variables_sharding), so fsdp-stored
    checkpoints evaluate without a host-side gather. The abstract
    model.init costs one host-side trace; its variables tree is what
    the sharding pins resolve against, and it matches any checkpoint of
    the same config by construction."""
    if mesh is None or not LAYOUT.has_seq(mesh):
        raise ValueError(
            "compute_sharding='halo' needs a mesh with a 'seq' axis — "
            "build one with make_mesh_fsdp(n_data, n_fsdp, n_seq) or "
            "make_mesh_2d(n_data, n_seq)")
    n_seq = LAYOUT.seq_size(mesh)
    h = 8 * n_seq * 3  # smallest halo-legal geometry; params are
    w = 64             # size-independent (fully convolutional)
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, h, w, 3), jnp.float32),
                           jnp.zeros((1, h, w, 3), jnp.float32),
                           iters=1, train=False, test_mode=True))
    halo_fn = halo.make_halo_eval_fn(cfg, mesh, abstract["params"],
                                     iters=iters)
    var_sh = variables_sharding(mesh, abstract)
    data = batch_input_sharding(mesh)  # P('data', 'seq')

    def step(
        variables: Dict[str, Any],
        image1: jax.Array,
        image2: jax.Array,
        flow_init: jax.Array,
    ):
        stats = variables.get("batch_stats", {})
        return halo_fn(variables["params"], stats, image1, image2,
                       flow_init)

    return jax.jit(
        step,
        in_shardings=(var_sh, data, data, data),
        out_shardings=(data, data),
    )


def make_encode_step(
    cfg: RAFTConfig,
    mesh: Optional[Mesh] = None,
) -> Callable[..., Dict[str, jax.Array]]:
    """Jitted per-frame encoder stage (RAFT mode="encode").

    (variables, frame [, edges]) -> the frame's feature dict {fmap, ctx
    [, efmap, ectx]} — everything a frame contributes to any pair it
    joins. The streaming video path runs this ONCE per new frame; the
    previous frame's dict comes from the device-resident session carry
    (serve.sessions.DeviceSessionStore), so a chained stream pays half
    the encoder FLOPs of repeated pair calls. Composes with
    :func:`make_refine_step` to reproduce the monolithic eval step
    exactly (parity pinned in tests/test_zzvideo.py).

    With a mesh, shardings pin like make_eval_step: variables
    replicated, frame batch (and every feature-dict leaf — all leaves
    are batch-leading >=3D) over the 'data' axis.
    """
    model = RAFT(cfg)

    def encode(
        variables: Dict[str, Any],
        frame: jax.Array,
        edges: Optional[jax.Array] = None,
    ) -> Dict[str, jax.Array]:
        return model.apply(variables, frame, edges1=edges, train=False,
                           mode="encode")

    if mesh is None:
        return jax.jit(encode)
    repl = replicated_sharding(mesh)
    data = batch_input_sharding(mesh)
    return jax.jit(encode, in_shardings=(repl, data, data),
                   out_shardings=data)


def make_refine_step(
    cfg: RAFTConfig,
    iters: int = 24,
    mesh: Optional[Mesh] = None,
    adaptive: bool = False,
) -> Callable[..., Tuple[jax.Array, ...]]:
    """Jitted refinement stage (RAFT mode="step"), test-mode returns.

    (variables, features1, features2, flow_init) -> (flow_low, flow_up)
    where features1 is the EARLIER frame's dict (its ctx seeds the GRU)
    and flow_init is always materialized (a zeros flow_init equals no
    warm start — the engine's one-executable-per-bucket contract).
    Same param tree as the monolithic step; checkpoints interchange.

    ``adaptive=True``: same contract extension as make_eval_step — a
    trailing traced ``iter_budget`` scalar and (flow_low, flow_up,
    iters_used[B], final_delta[B]) returns.
    """
    model = RAFT(cfg)

    def refine(
        variables: Dict[str, Any],
        features1: Dict[str, jax.Array],
        features2: Dict[str, jax.Array],
        flow_init: Optional[jax.Array] = None,
        iter_budget: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, ...]:
        kwargs: Dict[str, Any] = {}
        if adaptive:
            kwargs.update(adaptive=True, iter_budget=iter_budget)
        return model.apply(variables, None, iters=iters,
                           flow_init=flow_init, train=False,
                           test_mode=True, mode="step",
                           features1=features1, features2=features2,
                           **kwargs)

    if mesh is None:
        return jax.jit(refine)
    repl = replicated_sharding(mesh)
    data = batch_input_sharding(mesh)
    if adaptive:
        vec = batch_sharding(mesh)
        return jax.jit(refine,
                       in_shardings=(repl, data, data, data, repl),
                       out_shardings=(data, data, vec, vec))
    return jax.jit(refine, in_shardings=(repl, data, data, data),
                   out_shardings=(data, data))
