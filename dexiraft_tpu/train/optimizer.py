"""AdamW + OneCycle schedule + global-norm clipping.

Reproduces fetch_optimizer (train.py:80-87): AdamW(lr, wdecay, eps) under
torch OneCycleLR(max_lr=lr, total_steps=num_steps+100, pct_start=0.05,
anneal_strategy='linear'), with clip_grad_norm_(1.0) applied before the
step (train.py:182).
"""

from __future__ import annotations

import jax.numpy as jnp
import optax


def onecycle_lr(
    max_lr: float,
    total_steps: int,
    pct_start: float = 0.05,
    div_factor: float = 25.0,
    final_div_factor: float = 1e4,
):
    """Linear one-cycle schedule matching torch OneCycleLR(anneal='linear').

    Phase 1 (pct_start of total): linear  max_lr/div_factor -> max_lr.
    Phase 2 (rest):               linear  max_lr -> max_lr/(div_factor*final_div_factor).

    torch counts schedule steps from 1..total and errors past total; we
    clamp instead so the +100 slack steps (train.py:84) are harmless.
    """
    initial = max_lr / div_factor
    final = initial / final_div_factor
    # torch's phase boundary: float(pct_start * total_steps) - 1 steps in phase 1;
    # floor at a tiny positive value so degenerate totals (pct_start*total <= 1)
    # degrade to an immediate-peak schedule instead of 0/0 = NaN
    up_steps = max(pct_start * total_steps - 1.0, 1e-6)

    def schedule(step):
        step = jnp.minimum(jnp.asarray(step, jnp.float32), total_steps - 1.0)
        up = initial + (max_lr - initial) * jnp.minimum(step / up_steps, 1.0)
        down_frac = (step - up_steps) / ((total_steps - 1.0) - up_steps)
        down = max_lr + (final - max_lr) * jnp.clip(down_frac, 0.0, 1.0)
        return jnp.where(step <= up_steps, up, down)

    return schedule


def training_schedule(lr: float, num_steps: int):
    """The schedule actually used for training: OneCycle over num_steps+100
    (the reference's slack, train.py:84). Single source of truth for both
    the optimizer and the lr reported in metrics."""
    return onecycle_lr(lr, num_steps + 100)


def make_optimizer(
    lr: float,
    num_steps: int,
    wdecay: float = 1e-4,
    epsilon: float = 1e-8,
    clip: float = 1.0,
) -> optax.GradientTransformation:
    """clip-by-global-norm -> AdamW(OneCycle). Matches train.py:80-87."""
    schedule = training_schedule(lr, num_steps)
    tx = optax.adamw(schedule, b1=0.9, b2=0.999, eps=epsilon, weight_decay=wdecay)
    if clip and clip > 0:
        tx = optax.chain(optax.clip_by_global_norm(clip), tx)
    return tx
