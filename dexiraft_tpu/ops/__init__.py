"""Pure-function ops: sampling, correlation, upsampling, losses.

Every op here is shape-polymorphic, jit-safe (static shapes only), and has
a parity test against the reference semantics in tests/.
"""

from dexiraft_tpu.ops.grid import (
    bilinear_sampler,
    coords_grid,
    resize_bilinear_align_corners,
    upflow8,
)
from dexiraft_tpu.ops.corr import (
    all_pairs_correlation,
    build_corr_pyramid,
    corr_lookup,
    CorrPyramid,
)
from dexiraft_tpu.ops.quant import (
    CORR_DTYPES,
    corr_dtype_bytes,
    dequantize,
    quantize_symmetric,
    store_corr,
)
from dexiraft_tpu.ops.upsample import upsample_flow_convex
from dexiraft_tpu.ops.losses import sequence_loss, flow_metrics

__all__ = [
    "CORR_DTYPES",
    "corr_dtype_bytes",
    "dequantize",
    "quantize_symmetric",
    "store_corr",
    "bilinear_sampler",
    "coords_grid",
    "resize_bilinear_align_corners",
    "upflow8",
    "all_pairs_correlation",
    "build_corr_pyramid",
    "corr_lookup",
    "CorrPyramid",
    "upsample_flow_convex",
    "sequence_loss",
    "flow_metrics",
]
