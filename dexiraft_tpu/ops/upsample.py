"""Convex-combination flow upsampling (the learned 8x upsampler).

Reference: core/raft.py:87-98 — a 9-way softmax over 3x3 neighborhoods of
the coarse flow, predicted per 8x8 output sub-pixel. The reference uses
F.unfold; here the 3x3 patch extraction is nine shifted slices of a padded
array (XLA fuses these into one loop) and the combination is an einsum.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def upsample_flow_convex(flow: jax.Array, mask: jax.Array) -> jax.Array:
    """Upsample (B, H, W, 2) flow to (B, 8H, 8W, 2) by convex combination.

    mask: (B, H, W, 576) raw logits from the update block's mask head,
    laid out as 9 * (8*8) — kernel-position-major like the reference's
    ``mask.view(N, 1, 9, 8, 8, H, W)`` (core/raft.py:90), softmaxed over
    the 9 taps. Flow vectors are scaled by 8 (coarse pixels -> fine pixels).
    """
    b, h, w, _ = flow.shape
    m = mask.reshape(b, h, w, 9, 8, 8)
    m = jax.nn.softmax(m, axis=3)

    fp = jnp.pad(8.0 * flow, ((0, 0), (1, 1), (1, 1), (0, 0)))
    # Row-major 3x3 taps, matching F.unfold's kernel ordering (dy, then dx).
    patches = jnp.stack(
        [fp[:, dy : dy + h, dx : dx + w, :] for dy in range(3) for dx in range(3)],
        axis=3,
    )  # (B, H, W, 9, 2)

    up = jnp.einsum("bhwkij,bhwkc->bhwijc", m, patches)  # (B, H, W, 8, 8, 2)
    return up.transpose(0, 1, 3, 2, 4, 5).reshape(b, 8 * h, 8 * w, 2)
