"""Low-precision storage for the correlation volume / fmap2 pyramid.

The ~200 MB all-pairs pyramid (and the fmap2 pyramid the on-demand paths
stream every iteration) is the HBM-bandwidth term of the refinement loop
(docs/perf.md "Correlation memory & precision"). Storing it below fp32
halves (bf16) or quarters (int8) the bytes each lookup moves; the values
are dequantized *inside* the consuming matmul/kernel so no fp32 copy is
ever materialized in HBM.

Quantization is symmetric per-tensor (one fp32 scale per pyramid level):
correlation volumes are zero-centered dot products, so an asymmetric
zero-point buys nothing and would cost an extra add on the hot path.
Dequantization is exactly linear (x ~ scale * q), which is what lets the
scale be folded into whatever linear op consumes the values — the lookup
window blend, or the motion encoder's 1x1 conv weights in the fused
Pallas kernel (ops/pallas_corr.py).

Gradients: the bf16 cast is differentiable (cotangents cast back); the
int8 round is not — int8 is an inference-format, and the model layer
refuses to train with it (models/raft.py) rather than silently training
with dead fmap gradients.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# the CLI/config-facing vocabulary lives jax-free in config.py; this is
# the same tuple object, re-exported for ops-side callers
from dexiraft_tpu.config import CORR_DTYPES  # noqa: E402


def quantize_symmetric(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """x (any shape, float) -> (int8 values, fp32 scalar scale).

    scale = max|x| / 127, so dequantization ``q * scale`` covers the full
    observed range with per-value error <= scale/2. The max is guarded
    away from zero so an all-zero tensor quantizes to zeros with a finite
    scale instead of NaN.
    """
    if x.size == 0:
        # degenerate pyramid tail (a 1x1 level pools to zero rows) —
        # nothing to quantize, but the level must keep flowing through
        # the lookup's (empty) contractions with a well-defined scale
        return x.astype(jnp.int8), jnp.float32(1.0)
    amax = jnp.max(jnp.abs(x)).astype(jnp.float32)
    scale = jnp.maximum(amax, jnp.float32(1e-12)) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def store_corr(x: jax.Array, corr_dtype: str
               ) -> Tuple[jax.Array, Optional[jax.Array]]:
    """Cast a correlation-pyramid level to its storage dtype.

    Returns (stored array, scale) where scale is None for the
    scale-free dtypes (fp32/bf16) and a fp32 scalar for int8.
    """
    if corr_dtype == "fp32":
        return x.astype(jnp.float32), None
    if corr_dtype == "bf16":
        return x.astype(jnp.bfloat16), None
    if corr_dtype == "int8":
        return quantize_symmetric(x)
    raise ValueError(
        f"unknown corr_dtype {corr_dtype!r}; expected one of {CORR_DTYPES}")


def dequantize(x: jax.Array, scale: Optional[jax.Array]) -> jax.Array:
    """Stored level -> fp32 values. The inverse of store_corr; inside jit
    the convert fuses into the consuming matmul's operand read, so this
    costs no extra HBM pass."""
    out = x.astype(jnp.float32)
    if scale is not None:
        out = out * scale
    return out


def corr_dtype_bytes(corr_dtype: str) -> int:
    """Bytes per stored correlation value (the bytes-moved estimator of
    scripts/micro_bench.py --corr_dtype)."""
    if corr_dtype not in CORR_DTYPES:
        raise ValueError(
            f"unknown corr_dtype {corr_dtype!r}; expected one of {CORR_DTYPES}")
    return {"fp32": 4, "bf16": 2, "int8": 1}[corr_dtype]
