"""Coordinate grids and bilinear sampling.

TPU-native equivalents of the reference tensor utilities
(reference: core/utils/utils.py:57-82): ``coords_grid``, ``bilinear_sampler``
(same semantics as torch ``grid_sample(align_corners=True,
padding_mode='zeros')`` driven in pixel coordinates), and ``upflow8``.

All images are NHWC; coordinate channels are ordered (x, y) like the
reference's flow convention (core/utils/utils.py:74-77).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def coords_grid(batch: int, ht: int, wd: int, dtype=jnp.float32) -> jax.Array:
    """Pixel-center coordinate grid, shape (batch, ht, wd, 2), channels (x, y).

    Mirrors reference core/utils/utils.py:74-77 (which stacks meshgrid
    reversed so channel 0 is x/width, channel 1 is y/height).
    """
    x = jnp.arange(wd, dtype=dtype)
    y = jnp.arange(ht, dtype=dtype)
    xx, yy = jnp.meshgrid(x, y)  # both (ht, wd)
    grid = jnp.stack([xx, yy], axis=-1)
    return jnp.broadcast_to(grid[None], (batch, ht, wd, 2))


def bilinear_sampler(img: jax.Array, coords: jax.Array) -> jax.Array:
    """Bilinearly sample ``img`` at real-valued pixel ``coords``.

    img:    (N, H, W, C)
    coords: (N, h, w, 2) with channels (x, y) in *pixel* units — (0, 0) is
            the center of the top-left pixel, (W-1, H-1) of the bottom-right.
    returns (N, h, w, C)

    Semantics match ``F.grid_sample(..., align_corners=True,
    padding_mode='zeros')`` as wrapped by the reference
    (core/utils/utils.py:57-71): out-of-range corners contribute zero.
    """
    H, W = img.shape[1], img.shape[2]
    x = coords[..., 0]
    y = coords[..., 1]

    x0f = jnp.floor(x)
    y0f = jnp.floor(y)
    x0 = x0f.astype(jnp.int32)
    y0 = y0f.astype(jnp.int32)
    x1 = x0 + 1
    y1 = y0 + 1

    wx1 = (x - x0f).astype(img.dtype)
    wy1 = (y - y0f).astype(img.dtype)
    wx0 = 1.0 - wx1
    wy0 = 1.0 - wy1

    n = img.shape[0]
    bidx = jnp.arange(n, dtype=jnp.int32)[:, None, None]

    def corner(yi, xi, w):
        valid = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
        xc = jnp.clip(xi, 0, W - 1)
        yc = jnp.clip(yi, 0, H - 1)
        vals = img[bidx, yc, xc]  # (N, h, w, C)
        return vals * (w * valid.astype(img.dtype))[..., None]

    out = (
        corner(y0, x0, wy0 * wx0)
        + corner(y0, x1, wy0 * wx1)
        + corner(y1, x0, wy1 * wx0)
        + corner(y1, x1, wy1 * wx1)
    )
    return out


def _resize_matrix(n_in: int, n_out: int, dtype) -> jax.Array:
    """Static 1-D align_corners interpolation matrix (n_out, n_in).

    Output pixel o samples input coordinate o*(n_in-1)/(n_out-1); linear
    interpolation is the triangular hat kernel relu(1 - |p - t|).
    """
    t = (jnp.linspace(0.0, n_in - 1.0, n_out, dtype=jnp.float32)
         if n_out > 1 else jnp.zeros((1,), jnp.float32))
    pos = jnp.arange(n_in, dtype=jnp.float32)
    return jnp.maximum(0.0, 1.0 - jnp.abs(pos[None, :] - t[:, None])).astype(dtype)


def resize_bilinear_align_corners(img: jax.Array, ht: int, wd: int) -> jax.Array:
    """Bilinear resize with align_corners=True semantics (torch interpolate).

    ``jax.image.resize`` uses half-pixel centers, so we interpolate
    explicitly — and since the target grid is REGULAR, the resize is
    separable into two dense matmuls against static hat matrices (MXU
    work; per-pixel gather sampling is ~2 orders slower on TPU).
    """
    h, w = img.shape[1], img.shape[2]
    ry = _resize_matrix(h, ht, img.dtype)  # (ht, h)
    rx = _resize_matrix(w, wd, img.dtype)  # (wd, w)
    # HIGHEST precision: TPU matmul at DEFAULT truncates operands to
    # bf16, which the elementwise sampler this replaces never did
    out = jnp.einsum("oy,nyxc->noxc", ry, img,
                     precision=jax.lax.Precision.HIGHEST,
                     preferred_element_type=jnp.float32).astype(img.dtype)
    return jnp.einsum("px,noxc->nopc", rx, out,
                      precision=jax.lax.Precision.HIGHEST,
                      preferred_element_type=jnp.float32).astype(img.dtype)


def upflow8(flow: jax.Array) -> jax.Array:
    """8x bilinear upsample of a flow field, scaling the vectors by 8.

    Reference: core/utils/utils.py:80-82. flow is (N, H, W, 2).
    """
    h, w = flow.shape[1], flow.shape[2]
    return 8.0 * resize_bilinear_align_corners(flow, 8 * h, 8 * w)
