"""All-pairs 4D correlation volume: build, pyramid, windowed lookup.

TPU-native re-design of the reference centerpiece (core/corr.py:12-60):
the volume is one big batched matmul (MXU-friendly), the pyramid is
slice+reshape-mean 2x2 average pooling (NOT lax.reduce_window — see
avg_pool_2x2), and the per-iteration lookup gathers a (2r+1)^2 bilinear
window per pixel per level.

Layouts: feature maps are (B, H, W, D); the flattened volume is
(B*H*W, H_l, W_l, 1) per level — same flattening the reference uses so the
lookup is a plain batched 2D sample.

This module is the materialized path; the memory-efficient on-demand
equivalent of the reference's alt_cuda_corr CUDA kernel
(alt_cuda_corr/correlation_kernel.cu) is a separate op
(see dexiraft_tpu.ops.local_corr once built).
"""

from __future__ import annotations

from typing import List, Optional

import flax.struct
import jax
import jax.numpy as jnp

from dexiraft_tpu.ops.quant import store_corr


@flax.struct.dataclass
class CorrPyramid:
    """Correlation pyramid + lookup geometry.

    A pytree whose leaves are only the level arrays (and the per-level
    quantization scales, when present); the geometry ints are static aux
    data, so instances are safe to pass through jit boundaries and
    lax.scan carries without tracer leakage into shape arithmetic.
    """

    levels: tuple  # tuple of (B*H*W, H_l, W_l, 1) arrays (fp32/bf16/int8)
    batch: int = flax.struct.field(pytree_node=False)
    ht: int = flax.struct.field(pytree_node=False)
    wd: int = flax.struct.field(pytree_node=False)
    radius: int = flax.struct.field(pytree_node=False)
    # per-level fp32 scalar dequantization scales for int8 storage; None
    # for the scale-free dtypes (ops/quant.py). A pytree leaf tuple.
    scales: Optional[tuple] = None

    def __call__(self, coords: jax.Array) -> jax.Array:
        return corr_lookup(self, coords)


def all_pairs_correlation(fmap1: jax.Array, fmap2: jax.Array) -> jax.Array:
    """corr[b, i, j, k, l] = <fmap1[b,i,j,:], fmap2[b,k,l,:]> / sqrt(D).

    fmap1, fmap2: (B, H, W, D). Returns (B*H*W, H, W, 1) in float32 —
    the flattened layout the pyramid/lookup consume.
    Reference: core/corr.py:52-60 (matmul + /sqrt(dim)), fp32 like
    core/raft.py:139-142.
    """
    b, h, w, d = fmap1.shape
    h2, w2 = fmap2.shape[1:3]  # may differ from (h, w) when the query
    # axis is sharded (context parallelism, parallel/context.py)
    f1 = fmap1.reshape(b, h * w, d).astype(jnp.float32)
    f2 = fmap2.reshape(b, h2 * w2, d).astype(jnp.float32)
    corr = jnp.einsum("bnd,bmd->bnm", f1, f2, preferred_element_type=jnp.float32)
    corr = corr / jnp.sqrt(jnp.float32(d))
    return corr.reshape(b * h * w, h2, w2, 1)


def avg_pool_2x2(x: jax.Array) -> jax.Array:
    """2x2 stride-2 average pool over the spatial dims of (N, H, W, C).

    VALID padding so odd trailing rows/cols are dropped — exactly
    torch.nn.functional.avg_pool2d(x, 2, stride=2) (core/corr.py:26).

    Implemented as slice + reshape + mean rather than lax.reduce_window:
    identical numerics, cleanly differentiable in reverse mode (reduce_window
    linearization is unsupported on some backends), and XLA lowers it to the
    same windowed reduction.
    """
    n, h, w, c = x.shape
    h2, w2 = h // 2, w // 2
    x = x[:, : 2 * h2, : 2 * w2, :]
    x = x.reshape(n, h2, 2, w2, 2, c)
    return x.mean(axis=(2, 4))


def build_corr_pyramid(
    fmap1: jax.Array, fmap2: jax.Array, num_levels: int = 4, radius: int = 4,
    dtype: str = "fp32",
) -> CorrPyramid:
    """Materialize the all-pairs volume and its average-pool pyramid.

    Reference: core/corr.py:13-27. Level i has shape
    (B*H*W, H >> i, W >> i, 1) (floor division via VALID pooling).

    The reference pools the VOLUME; correlation is linear in fmap2, so
    avg-pooling the volume's target dims equals correlating against the
    avg-pooled fmap2 — mathematically identical (mean of dots = dot of
    mean), but each level is then a direct MXU matmul instead of strided
    2x2 pooling passes over the ~200 MB level-0 volume, which on TPU cost
    more than the matmul itself.

    ``dtype`` is the STORAGE precision of the pyramid ("fp32", "bf16",
    "int8" — ops/quant.py): correlation is always computed fp32, then
    each level is stored low-precision (per-level scale for int8) and
    dequantized inside the lookup's matmuls. This halves/quarters the
    HBM bytes every refinement iteration streams — the loop's bandwidth
    term (docs/perf.md "Correlation memory & precision").
    """
    b, h, w, _ = fmap1.shape
    f2 = fmap2
    levels: List[jax.Array] = []
    scales: List[Optional[jax.Array]] = []
    for _ in range(num_levels):
        lvl, scale = store_corr(all_pairs_correlation(fmap1, f2), dtype)
        levels.append(lvl)
        scales.append(scale)
        f2 = avg_pool_2x2(f2.astype(jnp.float32))
    return CorrPyramid(
        levels=tuple(levels), batch=b, ht=h, wd=w, radius=radius,
        scales=tuple(scales) if dtype == "int8" else None)


def _window_delta(radius: int, dtype=jnp.float32) -> jax.Array:
    """(2r+1, 2r+1, 2) offset lattice, channels (x-offset, y-offset).

    Matches the reference's ordering EXACTLY (core/corr.py:37-43): it
    stacks meshgrid(dy, dx) onto (x, y) centroids, so the x offset varies
    along window axis 0 and the y offset along axis 1 (a transposed
    window). Bit-compatibility here is what lets reference-trained
    checkpoints load via interop.torch_convert — the motion encoder's
    first conv consumes these 324 channels in this order.
    """
    d = jnp.arange(-radius, radius + 1, dtype=dtype)
    di, dj = jnp.meshgrid(d, d, indexing="ij")  # di varies along axis 0
    return jnp.stack([di, dj], axis=-1)  # (x + di, y + dj)


def _axis_interp_matrix(center: jax.Array, radius: int, size: int,
                        offset=0) -> jax.Array:
    """Per-pixel 1-D bilinear selection matrix A (N, 2r+1, size).

    Row j interpolates the axis at coordinate t = c_n + (j - radius);
    linear interpolation between floor(t) and floor(t)+1 is exactly the
    triangular hat kernel, so A[n, j, p] = relu(1 - |p - t|) — one fused
    elementwise expression, and out-of-range taps have empty support,
    reproducing the zero padding of bilinear_sampler /
    F.grid_sample(zeros). d/dc matches grid_sample's coordinate gradient
    almost everywhere.

    ``offset`` shifts the axis positions: column p represents global
    coordinate offset + p (used by ring context parallelism, where each
    chip holds a row BLOCK of the target axis).
    """
    t = center[:, None] + jnp.arange(-radius, radius + 1,
                                     dtype=jnp.float32)  # (N, win)
    pos = offset + jnp.arange(size, dtype=jnp.float32)[None, None, :]
    return jnp.maximum(0.0, 1.0 - jnp.abs(pos - t[..., None]))


def interp_window(vol: jax.Array, centers: jax.Array, radius: int,
                  scale: Optional[jax.Array] = None) -> jax.Array:
    """Bilinear (2r+1)^2 window of each volume slab around its center.

    vol (N, Hl, Wl), centers (N, 2) in level pixels -> (N, (2r+1)^2).

    ``vol`` may be stored below fp32 (bf16/int8 pyramid, ops/quant.py):
    the upcast happens inside the einsum's operand read (XLA fuses the
    convert into the matmul, so the fp32 values never round-trip HBM),
    and ``scale`` — the int8 dequantization factor — multiplies the
    window afterwards, which is exact because the whole lookup is linear
    in the volume.

    TPU formulation: the taps sit at INTEGER offsets from one real-valued
    center per slab, so every tap shares the slab's fractional part and
    the 2-D bilinear interpolation separates into per-axis 1-D stencils.
    The whole windowed gather then collapses into batched matmuls
    against per-pixel one-hot interpolation matrices,

        window[n] = A_x[n] · vol[n]ᵀ · A_y[n]ᵀ   — MXU work, no gather,

    which XLA schedules as streaming passes over the volume (HBM-bandwidth
    bound) instead of the scalar-gather HLO that advanced indexing lowers
    to (~1000x slower on TPU measured at Sintel eval size). Expressed as
    ONE three-operand einsum so XLA picks the contraction path itself:
    measured on-chip (scripts/lookup_ab2.py, RTT-corrected) 1.2 ms/iter
    vs 2.2 for the hand-split y-then-x pair and 1.5 for x-then-y.

    The window axis order matches _window_delta: x offset on the SLOW
    axis — the reference's transposed window (core/corr.py:37-43).
    """
    win = 2 * radius + 1
    hl, wl = vol.shape[1], vol.shape[2]
    ax = _axis_interp_matrix(centers[:, 0], radius, wl)  # (N, win, Wl)
    ay = _axis_interp_matrix(centers[:, 1], radius, hl)  # (N, win, Hl)
    # upcast in the operand read (fuses into the matmul; TPU's default
    # matmul precision truncates fp32 inputs to bf16 internally anyway —
    # lookup_ab3's finding — so the storage dtype only changes HBM bytes)
    window = jnp.einsum("nby,nyx,nax->nab", ay, vol.astype(jnp.float32), ax,
                        preferred_element_type=jnp.float32)
    if scale is not None:
        window = window * scale
    return window.reshape(vol.shape[0], win * win)


def corr_lookup(pyramid: CorrPyramid, coords: jax.Array) -> jax.Array:
    """Sample a (2r+1)^2 window around ``coords / 2^i`` at every level.

    coords: (B, H, W, 2) current correspondence estimates in level-0 pixels.
    Returns (B, H, W, num_levels * (2r+1)^2) float32 correlation features.
    Reference: core/corr.py:29-50; windowing via interp_window.
    """
    r = pyramid.radius
    b, h, w = pyramid.batch, pyramid.ht, pyramid.wd
    win = 2 * r + 1

    flat = coords.reshape(b * h * w, 2).astype(jnp.float32)
    out = []
    for i, corr in enumerate(pyramid.levels):
        scale = pyramid.scales[i] if pyramid.scales is not None else None
        window = interp_window(corr[..., 0], flat / (2.0**i), r, scale=scale)
        out.append(window.reshape(b, h, w, win * win))
    return jnp.concatenate(out, axis=-1).astype(jnp.float32)
