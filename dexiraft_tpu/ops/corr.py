"""All-pairs 4D correlation volume: build, pyramid, windowed lookup.

TPU-native re-design of the reference centerpiece (core/corr.py:12-60):
the volume is one big batched matmul (MXU-friendly), the pyramid is
slice+reshape-mean 2x2 average pooling (NOT lax.reduce_window — see
avg_pool_2x2), and the per-iteration lookup gathers a (2r+1)^2 bilinear
window per pixel per level.

Layouts: feature maps are (B, H, W, D); the flattened volume is
(B*H*W, H_l, W_l, 1) per level — same flattening the reference uses so the
lookup is a plain batched 2D sample.

This module is the materialized path; the memory-efficient on-demand
equivalent of the reference's alt_cuda_corr CUDA kernel
(alt_cuda_corr/correlation_kernel.cu) is a separate op
(see dexiraft_tpu.ops.local_corr once built).
"""

from __future__ import annotations

from typing import List

import flax.struct
import jax
import jax.numpy as jnp

from dexiraft_tpu.ops.grid import bilinear_sampler


@flax.struct.dataclass
class CorrPyramid:
    """Correlation pyramid + lookup geometry.

    A pytree whose leaves are only the level arrays; the geometry ints are
    static aux data, so instances are safe to pass through jit boundaries
    and lax.scan carries without tracer leakage into shape arithmetic.
    """

    levels: tuple  # tuple of (B*H*W, H_l, W_l, 1) arrays
    batch: int = flax.struct.field(pytree_node=False)
    ht: int = flax.struct.field(pytree_node=False)
    wd: int = flax.struct.field(pytree_node=False)
    radius: int = flax.struct.field(pytree_node=False)

    def __call__(self, coords: jax.Array) -> jax.Array:
        return corr_lookup(self, coords)


def all_pairs_correlation(fmap1: jax.Array, fmap2: jax.Array) -> jax.Array:
    """corr[b, i, j, k, l] = <fmap1[b,i,j,:], fmap2[b,k,l,:]> / sqrt(D).

    fmap1, fmap2: (B, H, W, D). Returns (B*H*W, H, W, 1) in float32 —
    the flattened layout the pyramid/lookup consume.
    Reference: core/corr.py:52-60 (matmul + /sqrt(dim)), fp32 like
    core/raft.py:139-142.
    """
    b, h, w, d = fmap1.shape
    h2, w2 = fmap2.shape[1:3]  # may differ from (h, w) when the query
    # axis is sharded (context parallelism, parallel/context.py)
    f1 = fmap1.reshape(b, h * w, d).astype(jnp.float32)
    f2 = fmap2.reshape(b, h2 * w2, d).astype(jnp.float32)
    corr = jnp.einsum("bnd,bmd->bnm", f1, f2, preferred_element_type=jnp.float32)
    corr = corr / jnp.sqrt(jnp.float32(d))
    return corr.reshape(b * h * w, h2, w2, 1)


def avg_pool_2x2(x: jax.Array) -> jax.Array:
    """2x2 stride-2 average pool over the spatial dims of (N, H, W, C).

    VALID padding so odd trailing rows/cols are dropped — exactly
    torch.nn.functional.avg_pool2d(x, 2, stride=2) (core/corr.py:26).

    Implemented as slice + reshape + mean rather than lax.reduce_window:
    identical numerics, cleanly differentiable in reverse mode (reduce_window
    linearization is unsupported on some backends), and XLA lowers it to the
    same windowed reduction.
    """
    n, h, w, c = x.shape
    h2, w2 = h // 2, w // 2
    x = x[:, : 2 * h2, : 2 * w2, :]
    x = x.reshape(n, h2, 2, w2, 2, c)
    return x.mean(axis=(2, 4))


def build_corr_pyramid(
    fmap1: jax.Array, fmap2: jax.Array, num_levels: int = 4, radius: int = 4
) -> CorrPyramid:
    """Materialize the all-pairs volume and its average-pool pyramid.

    Reference: core/corr.py:13-27. Level i has shape
    (B*H*W, H >> i, W >> i, 1) (floor division via VALID pooling).
    """
    b, h, w, _ = fmap1.shape
    corr = all_pairs_correlation(fmap1, fmap2)
    levels: List[jax.Array] = [corr]
    for _ in range(num_levels - 1):
        corr = avg_pool_2x2(corr)
        levels.append(corr)
    return CorrPyramid(levels=tuple(levels), batch=b, ht=h, wd=w, radius=radius)


def _window_delta(radius: int, dtype=jnp.float32) -> jax.Array:
    """(2r+1, 2r+1, 2) offset lattice, channels (x-offset, y-offset).

    Matches the reference's ordering EXACTLY (core/corr.py:37-43): it
    stacks meshgrid(dy, dx) onto (x, y) centroids, so the x offset varies
    along window axis 0 and the y offset along axis 1 (a transposed
    window). Bit-compatibility here is what lets reference-trained
    checkpoints load via interop.torch_convert — the motion encoder's
    first conv consumes these 324 channels in this order.
    """
    d = jnp.arange(-radius, radius + 1, dtype=dtype)
    di, dj = jnp.meshgrid(d, d, indexing="ij")  # di varies along axis 0
    return jnp.stack([di, dj], axis=-1)  # (x + di, y + dj)


def corr_lookup(pyramid: CorrPyramid, coords: jax.Array) -> jax.Array:
    """Sample a (2r+1)^2 window around ``coords / 2^i`` at every level.

    coords: (B, H, W, 2) current correspondence estimates in level-0 pixels.
    Returns (B, H, W, num_levels * (2r+1)^2) float32 correlation features.
    Reference: core/corr.py:29-50.
    """
    r = pyramid.radius
    b, h, w = pyramid.batch, pyramid.ht, pyramid.wd
    win = 2 * r + 1
    delta = _window_delta(r, dtype=coords.dtype)  # (win, win, 2)

    flat = coords.reshape(b * h * w, 1, 1, 2)
    out = []
    for i, corr in enumerate(pyramid.levels):
        centroid = flat / (2.0**i)
        coords_lvl = centroid + delta[None]  # (BHW, win, win, 2)
        sampled = bilinear_sampler(corr, coords_lvl)  # (BHW, win, win, 1)
        out.append(sampled.reshape(b, h, w, win * win))
    return jnp.concatenate(out, axis=-1).astype(jnp.float32)
