"""Flow training losses and metrics.

``sequence_loss`` reproduces the reference's gamma-weighted L1 over all
refinement iterations (train.py:48-73), including its exact masking
semantics: invalid pixels are zeroed but still counted in the mean.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

MAX_FLOW = 400.0


def flow_metrics(flow_pred: jax.Array, flow_gt: jax.Array, valid: jax.Array) -> Dict[str, jax.Array]:
    """End-point-error stats over valid pixels.

    flow_pred/flow_gt: (B, H, W, 2); valid: (B, H, W) boolean.
    Matches train.py:63-71 (masked mean EPE and <1/3/5 px rates).
    """
    epe = jnp.sqrt(jnp.sum((flow_pred - flow_gt) ** 2, axis=-1))
    v = valid.astype(jnp.float32)
    denom = jnp.maximum(v.sum(), 1.0)

    def masked_mean(x):
        return jnp.sum(x * v) / denom

    return {
        "epe": masked_mean(epe),
        "1px": masked_mean((epe < 1.0).astype(jnp.float32)),
        "3px": masked_mean((epe < 3.0).astype(jnp.float32)),
        "5px": masked_mean((epe < 5.0).astype(jnp.float32)),
    }


def sequence_loss(
    flow_preds: jax.Array,
    flow_gt: jax.Array,
    valid: jax.Array,
    gamma: float = 0.8,
    max_flow: float = MAX_FLOW,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Exponentially weighted L1 loss over the iteration sequence.

    flow_preds: (iters, B, H, W, 2) — the stacked per-iteration upsampled
    flows (the reference's python list, train.py:51).
    flow_gt: (B, H, W, 2); valid: (B, H, W) float or bool.

    Weight for prediction i of n is gamma**(n-1-i) (train.py:58-61); the
    per-iteration term is mean over *all* pixels with invalid ones zeroed —
    NOT a masked mean — matching train.py:61 exactly.
    """
    n = flow_preds.shape[0]
    mag = jnp.sqrt(jnp.sum(flow_gt**2, axis=-1))
    valid_mask = (valid >= 0.5) & (mag < max_flow)
    vf = valid_mask.astype(jnp.float32)[None, ..., None]  # (1, B, H, W, 1)

    weights = gamma ** jnp.arange(n - 1, -1, -1, dtype=jnp.float32)  # (n,)
    i_loss = jnp.abs(flow_preds - flow_gt[None])
    per_iter = jnp.mean(vf * i_loss, axis=(1, 2, 3, 4))  # (n,)
    flow_loss = jnp.sum(weights * per_iter)

    metrics = flow_metrics(flow_preds[-1], flow_gt, valid_mask)
    return flow_loss, metrics
