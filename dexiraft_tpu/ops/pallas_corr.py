"""Pallas TPU kernel for the local correlation lookup.

The tpu-native twin of alt_cuda_corr/correlation_kernel.cu:19-119, in the
gather formulation (SURVEY.md §2.2): the CUDA kernel stages fmap tiles
through __shared__ memory and scatter-accumulates bilinear corner weights;
here the (zero-padded) fmap2 level lives in VMEM, each grid step owns a
block of P query pixels, and per pixel we

  1. dynamic-slice the (2r+2, 2r+2, C) integer patch around floor(coords)
     (VMEM load driven by SMEM-resident scalar indices),
  2. dot against the pixel's fmap1 row on the VPU (fp32 accumulate),
  3. mask out-of-frame lattice points (zero-padding semantics of
     bilinear_sampler / F.grid_sample(zeros)),

then blend the 4 bilinear corners vectorized over the whole block.

Index preparation happens in XLA: coords are clipped to [-r-1, size+r]
(out-of-range windows are provably all-zero there because the clip bounds
are integers, so the +1 corner weight vanishes at the boundary), and fmap2
is zero-padded by 2r+2 so every clipped window is a legal static-size
slice.

Gradients: forward-only kernel wrapped in jax.custom_vjp; the VJP
recomputes through the XLA gather formulation (local_corr_level), giving
fmap gradients and zero coords gradient — the CUDA backward's semantics
(correlation_kernel.cu:307) without a second hand-written kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dexiraft_tpu.ops.local_corr import local_corr_level

# queries per grid step; read through _pixel_block() so on-chip tuning
# (scripts/tpu_smoke.py sweeps DEXIRAFT_PALLAS_PIXEL_BLOCK) needs no
# code edit. Resolved at trace time — rebuild the jit to change it.
_PIXEL_BLOCK = 256


def _pixel_block() -> int:
    import os

    # the batched variant stages (P, k, k, C) fp32 patches in VMEM
    # (~100 KiB per pixel at C=256, r=4), so its default block must be
    # much smaller than the loop kernel's
    default = 32 if _variant() == "batched" else _PIXEL_BLOCK
    # clamp: a bad flag must fail soft, not as a ZeroDivisionError deep
    # inside jit tracing
    return max(1, int(os.environ.get("DEXIRAFT_PALLAS_PIXEL_BLOCK",
                                     default)))


def _interpret_default() -> bool:
    # DEXIRAFT_PALLAS_INTERPRET=1 runs the kernel in interpreter mode
    # (trace-time switch) — lets the whole-model corr_impl="pallas" path
    # run off-chip (tests/test_local_corr.py). Never set it on a TPU
    # host: the interpreter is orders of magnitude slower.
    import os

    return os.environ.get("DEXIRAFT_PALLAS_INTERPRET", "0") == "1"


def _variant() -> str:
    # "loop": the original per-pixel slice+reduce kernel.
    # "batched": per-pixel work reduced to a pure patch COPY into a
    # (P, k, k, C) scratch, then ONE vectorized multiply-reduce over the
    # whole block — the shape the VPU pipelines well (the per-pixel
    # (k,k,C) reduce of "loop" is latency-bound, VERDICT r4 weak-6).
    # Costs P*k*k*C*4 B of extra VMEM, so "batched" wants a SMALLER
    # pixel block (default 32 vs 256). Trace-time switch; the on-chip
    # A/B lives in scripts/tpu_smoke.py.
    import os

    v = os.environ.get("DEXIRAFT_PALLAS_VARIANT", "loop")
    return v if v in ("loop", "batched") else "loop"


def _blend_corners_val(lattice, frac_ref):
    """Bilinear-blend the (P, k, k) integer-lattice dots into a
    (P, win*win) window value, x offset on the slow axis (the reference
    channel order — ops.corr)."""
    p_block, k, _ = lattice.shape
    win = k - 1
    fx = frac_ref[0, :, 0].reshape(p_block, 1, 1)
    fy = frac_ref[0, :, 1].reshape(p_block, 1, 1)
    tl = lattice[:, 0:win, 0:win]
    tr = lattice[:, 0:win, 1:win + 1]
    bl = lattice[:, 1:win + 1, 0:win]
    br = lattice[:, 1:win + 1, 1:win + 1]
    out = ((1 - fy) * (1 - fx) * tl + (1 - fy) * fx * tr
           + fy * (1 - fx) * bl + fy * fx * br)
    return out.swapaxes(1, 2).reshape(p_block, win * win)


def _blend_corners(lattice, frac_ref, out_ref):
    out_ref[0] = _blend_corners_val(lattice, frac_ref)


def _corr_kernel_batched(sx_ref, sy_ref, f1_ref, f2_ref, frac_ref,
                         sxv_ref, syv_ref, out_ref, patches_ref,
                         *, radius: int, h2: int, w2: int):
    r = radius
    k = 2 * r + 2
    p_block = f1_ref.shape[1]
    c = f1_ref.shape[2]
    inv_sqrt_c = 1.0 / (c ** 0.5)

    # phase 1: pure data movement — stage every pixel's (k, k, C) patch
    # into the block scratch; no per-pixel compute on the critical path
    def body(p, _):
        sx = sx_ref[0, p]
        sy = sy_ref[0, p]
        patches_ref[pl.ds(p, 1)] = (
            f2_ref[0, pl.ds(sy, k), pl.ds(sx, k), :].astype(jnp.float32)[None])
        return 0

    jax.lax.fori_loop(0, p_block, body, 0)

    # phase 2: ONE vectorized multiply-reduce over the whole block
    patches = patches_ref[:].astype(jnp.float32)          # (P, k, k, C)
    f1 = f1_ref[0].astype(jnp.float32)                    # (P, C)
    dots = jnp.sum(patches * f1[:, None, None, :], axis=3)  # (P, k, k)

    # vectorized out-of-frame mask: true lattice origin per pixel is
    # (sx - (r + 2), sy - (r + 2)) — see the loop kernel's derivation
    sxv = sxv_ref[0]                                      # (P,) int32
    syv = syv_ref[0]
    gx = (jax.lax.broadcasted_iota(jnp.int32, (p_block, k, k), 2)
          + (sxv - 2 - 2 * r)[:, None, None])
    gy = (jax.lax.broadcasted_iota(jnp.int32, (p_block, k, k), 1)
          + (syv - 2 - 2 * r)[:, None, None])
    valid = (gx >= 0) & (gx < w2) & (gy >= 0) & (gy < h2)
    dots = jnp.where(valid, dots * inv_sqrt_c, 0.0)
    _blend_corners(dots, frac_ref, out_ref)


def _fill_lattice_dots(sx_ref, sy_ref, f1_ref, f2_ref, lattice_ref,
                       *, radius: int, h2: int, w2: int):
    """Per-pixel slice+dot+mask loop shared by the per-level loop kernel
    and the fused kernel: stage each pixel's (k, k) integer-lattice dots
    (fp32 accumulate, storage dtype upcast in-register) into lattice_ref.

    Masking: lattice points outside the ORIGINAL (unpadded) frame read
    zero; slice starts were clipped into the padded frame, so the true
    lattice origin is recomputed as x0 = sx - (r + 2), y0 = sy - (r + 2).
    """
    r = radius
    k = 2 * r + 2
    p_block = f1_ref.shape[1]
    c = f1_ref.shape[2]
    inv_sqrt_c = 1.0 / (c ** 0.5)

    def body(p, _):
        sx = sx_ref[0, p]
        sy = sy_ref[0, p]
        patch = f2_ref[0, pl.ds(sy, k), pl.ds(sx, k), :]  # (k, k, C)
        f1p = f1_ref[0, p, :]  # (C,)
        dots = jnp.sum(
            patch.astype(jnp.float32) * f1p.astype(jnp.float32)[None, None, :],
            axis=2,
        )  # (k, k)
        gx = jax.lax.broadcasted_iota(jnp.int32, (k, k), 1) + (sx - 2 - 2 * r)
        gy = jax.lax.broadcasted_iota(jnp.int32, (k, k), 0) + (sy - 2 - 2 * r)
        valid = ((gx >= 0) & (gx < w2) & (gy >= 0) & (gy < h2))
        dots = jnp.where(valid, dots * inv_sqrt_c, 0.0)
        lattice_ref[p, :] = dots.reshape(k * k)
        return 0

    jax.lax.fori_loop(0, p_block, body, 0)


def _corr_kernel(sx_ref, sy_ref, f1_ref, f2_ref, frac_ref, out_ref,
                 lattice_ref, *, radius: int, h2: int, w2: int):
    k = 2 * radius + 2
    p_block = f1_ref.shape[1]
    _fill_lattice_dots(sx_ref, sy_ref, f1_ref, f2_ref, lattice_ref,
                       radius=radius, h2=h2, w2=w2)
    _blend_corners(lattice_ref[:].reshape(p_block, k, k), frac_ref, out_ref)


def _pallas_forward(fmap1: jax.Array, fmap2: jax.Array, coords: jax.Array,
                    radius: int, interpret=None) -> jax.Array:
    if interpret is None:
        interpret = _interpret_default()
    b, h, w, c = fmap1.shape
    h2, w2 = fmap2.shape[1:3]
    r = radius
    k = 2 * r + 2
    win = 2 * r + 1
    pad = k  # 2r+2 zeros on every side

    # ---- XLA-side index prep (shared with the fused kernel; slice
    # start in the padded frame is x0 - r + pad = x0 + r + 2, in range
    # [1, w2 + 2r + 2] given the clip — always a legal k-slice) ----
    sx, sy, frac = _index_prep(coords, h2, w2, r)

    # pad in the STORAGE dtype (fp32/bf16/int8 — ops/quant.py): the
    # quantized bytes are what stream HBM->VMEM; the kernel upcasts each
    # patch in-register (patch.astype(f32) in the dot)
    f2p = jnp.pad(fmap2, ((0, 0), (pad, pad), (pad, pad), (0, 0)))

    # flatten pixels, pad to the block size
    pixel_block = _pixel_block()
    n = h * w
    n_pad = (-n) % pixel_block
    np_tot = n + n_pad
    flat = lambda a, d: jnp.pad(a.reshape(b, n, *a.shape[3:]),
                                ((0, 0), (0, n_pad)) + ((0, 0),) * d)
    f1_flat = flat(fmap1.astype(jnp.float32), 1)
    sx_flat = flat(sx, 0)  # padded pixels read slice start 0 — harmless
    sy_flat = flat(sy, 0)
    frac_flat = flat(frac, 1)

    grid = (b, np_tot // pixel_block)
    smem_spec = pl.BlockSpec((1, pixel_block), lambda bi, ti: (bi, ti),
                             memory_space=pltpu.SMEM)
    vmem_vec_spec = pl.BlockSpec((1, pixel_block), lambda bi, ti: (bi, ti),
                                 memory_space=pltpu.VMEM)
    f1_spec = pl.BlockSpec((1, pixel_block, c), lambda bi, ti: (bi, ti, 0),
                           memory_space=pltpu.VMEM)
    f2_spec = pl.BlockSpec((1, h2 + 2 * pad, w2 + 2 * pad, c),
                           lambda bi, ti: (bi, 0, 0, 0),
                           memory_space=pltpu.VMEM)
    frac_spec = pl.BlockSpec((1, pixel_block, 2), lambda bi, ti: (bi, ti, 0),
                             memory_space=pltpu.VMEM)
    out_specs = pl.BlockSpec((1, pixel_block, win * win),
                             lambda bi, ti: (bi, ti, 0),
                             memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((b, np_tot, win * win), jnp.float32)

    if _variant() == "batched":
        kernel = functools.partial(_corr_kernel_batched, radius=r,
                                   h2=h2, w2=w2)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            # slice starts twice: SMEM scalars drive the dynamic patch
            # slices, VMEM vectors feed the vectorized lattice mask
            in_specs=[smem_spec, smem_spec, f1_spec, f2_spec, frac_spec,
                      vmem_vec_spec, vmem_vec_spec],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((pixel_block, k, k, c), jnp.float32)],
            interpret=interpret,
        )(sx_flat, sy_flat, f1_flat, f2p, frac_flat, sx_flat, sy_flat)
    else:
        kernel = functools.partial(_corr_kernel, radius=r, h2=h2, w2=w2)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[smem_spec, smem_spec, f1_spec, f2_spec, frac_spec],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((pixel_block, k * k), jnp.float32)],
            interpret=interpret,
        )(sx_flat, sy_flat, f1_flat, f2p, frac_flat)

    return out[:, :n].reshape(b, h, w, win * win)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_local_corr_level(fmap1, fmap2, coords, radius: int,
                            interpret=None, row_chunk=8):
    """(B,H,W,C) x (B,H2,W2,C) x (B,H,W,2 level coords) -> (B,H,W,(2r+1)^2).

    interpret=None defers to DEXIRAFT_PALLAS_INTERPRET (off-chip debug
    switch, resolved at trace time). row_chunk only affects the backward
    recompute (the forward kernel is already pixel-blocked); pass the
    model's corr_row_chunk so the VJP's transient patch buffer honors
    the same bound.
    """
    return _pallas_forward(fmap1, fmap2, coords, radius, interpret)


def _fwd(fmap1, fmap2, coords, radius, interpret, row_chunk):
    return (_pallas_forward(fmap1, fmap2, coords, radius, interpret),
            (fmap1, fmap2, coords))


def _bwd(radius, interpret, row_chunk, res, g):
    fmap1, fmap2, coords = res
    # row-chunked recompute: bounds the backward's transient patch buffer
    # the same way the forward XLA path does
    _, vjp = jax.vjp(
        lambda f1, f2: local_corr_level(f1, f2, coords, radius,
                                        row_chunk=row_chunk),
        fmap1, fmap2)
    g1, g2 = vjp(g)
    return g1, g2, jnp.zeros_like(coords)


pallas_local_corr_level.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# Fused refinement-step kernel: 4-level lookup + motion-encoder entry
# ---------------------------------------------------------------------------
#
# The per-level kernel above still writes each level's (B, H, W, win^2)
# window to HBM, where XLA's motion encoder reads the concatenated
# (B, H, W, L*win^2) tensor back for its 1x1 corr conv — two full HBM
# round-trips of the widest activation in the refinement loop. The fused
# kernel does the whole chain in ONE pallas_call per iteration: every
# pyramid level's window is computed while the pixel block's patches are
# VMEM-resident and immediately contracted against that level's slice of
# the motion encoder's 1x1 conv weight (an MXU matmul), so only the
# (B, H, W, F) conv OUTPUT ever touches HBM. F=256 vs L*win^2=324 plus
# the per-level intermediates: the loop's widest tensors never leave
# VMEM. Division of labor for the linear factors: the kernel applies
# 1/sqrt(C) itself (inside _fill_lattice_dots, same as the per-level
# kernel — do NOT fold it into the weights too); the caller folds ONLY
# the per-level int8 dequantization scales into the weight slices
# (models/update.py FusedCorrEncoder). The kernel reads the pyramid in
# its storage dtype (fp32/bf16/int8) and upcasts in-register.


def _fused_kernel(*refs, radius: int, num_levels: int, level_shapes: tuple):
    """refs: f1, w, b, then [sx, sy, frac, f2p] per level, out, lattice.

    Per level: the per-pixel patch slice+dot of _corr_kernel, the corner
    blend, then window @ w_level accumulated into the block's (P, F)
    output — all while resident in VMEM.
    """
    f1_ref, w_ref, b_ref = refs[0], refs[1], refs[2]
    lvl_refs = refs[3:3 + 4 * num_levels]
    out_ref, lattice_ref = refs[3 + 4 * num_levels], refs[4 + 4 * num_levels]

    r = radius
    k = 2 * r + 2
    win = 2 * r + 1
    p_block = f1_ref.shape[1]

    acc = jnp.broadcast_to(b_ref[0].astype(jnp.float32),
                           (p_block, b_ref.shape[1]))
    for lvl in range(num_levels):
        sx_ref, sy_ref, frac_ref, f2_ref = lvl_refs[4 * lvl:4 * lvl + 4]
        h2, w2 = level_shapes[lvl]
        # same per-pixel slice+dot+mask as the per-level loop kernel
        # (shared helper — ONE copy of the lattice-origin arithmetic)
        _fill_lattice_dots(sx_ref, sy_ref, f1_ref, f2_ref, lattice_ref,
                           radius=r, h2=h2, w2=w2)
        window = _blend_corners_val(
            lattice_ref[:].reshape(p_block, k, k), frac_ref)  # (P, win^2)
        w_lvl = w_ref[pl.ds(lvl * win * win, win * win), :]
        acc = acc + jnp.dot(window, w_lvl.astype(jnp.float32),
                            preferred_element_type=jnp.float32)
    out_ref[0] = acc


def _index_prep(coords: jax.Array, h2: int, w2: int, radius: int):
    """XLA-side index prep for one level (the same clip/floor/frac as
    _pallas_forward, at this level's geometry)."""
    r = radius
    x = jnp.clip(coords[..., 0].astype(jnp.float32),
                 -(r + 1.0), w2 - 1 + r + 1.0)
    y = jnp.clip(coords[..., 1].astype(jnp.float32),
                 -(r + 1.0), h2 - 1 + r + 1.0)
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    frac = jnp.stack([x - x0, y - y0], axis=-1)
    sx = x0.astype(jnp.int32) + (r + 2)
    sy = y0.astype(jnp.int32) + (r + 2)
    return sx, sy, frac


# combined VMEM budget for the padded fmap2 levels a single fused call
# may stage (bytes). ~16 MiB/core total minus the f1/weight/out/lattice
# blocks and double-buffering headroom. At the 440x1024 eval geometry the
# four padded fp32 levels need ~18 MB — over budget — so the fp32 fused
# path splits into per-level fused calls (each holds ONE level, the
# footprint the per-level kernel already proves fits); bf16 (~9 MB) and
# int8 (~4.5 MB) stay single-call, which is the configuration the fused
# kernel exists for. Env-overridable for on-chip tuning.
_FUSED_LEVELS_VMEM_BYTES = 12 * 1024 * 1024


def _fused_levels_budget() -> int:
    import os

    return int(os.environ.get("DEXIRAFT_FUSED_LEVELS_VMEM_BYTES",
                              _FUSED_LEVELS_VMEM_BYTES))


def _fused_forward(fmap1: jax.Array, fmap2_levels: tuple, coords: jax.Array,
                   weight: jax.Array, bias: jax.Array, radius: int,
                   interpret=None) -> jax.Array:
    if interpret is None:
        interpret = _interpret_default()
    b, h, w, c = fmap1.shape
    r = radius
    k = 2 * r + 2
    win = 2 * r + 1
    pad = k
    num_levels = len(fmap2_levels)
    feat = weight.shape[1]
    level_shapes = tuple(f2.shape[1:3] for f2 in fmap2_levels)

    if num_levels > 1:
        staged = sum((h2 + 2 * pad) * (w2 + 2 * pad) * c * f2.dtype.itemsize
                     for (h2, w2), f2 in zip(level_shapes, fmap2_levels))
        if staged > _fused_levels_budget():
            # over the VMEM budget (fp32 pyramid at large geometry):
            # one fused lookup+conv call PER level — each stages a single
            # level, still contracting its window against the weight
            # slice in-kernel, and the (B, H, W, win^2) per-level corr
            # features still never materialize; only L partial (B,H,W,F)
            # products are summed in XLA. Exactly linear, so identical
            # to the single-call result up to summation order.
            ww = win * win
            out = None
            zero_bias = jnp.zeros_like(bias)
            for lvl in range(num_levels):
                o = _fused_forward(
                    fmap1, (fmap2_levels[lvl],), coords / (2.0 ** lvl),
                    weight[lvl * ww:(lvl + 1) * ww], zero_bias, radius,
                    interpret)
                out = o if out is None else out + o
            return out + bias.astype(jnp.float32)

    import os

    # the fused kernel has the loop kernel's VMEM shape (one (P, k*k)
    # lattice scratch), so it shares the loop default — not the batched
    # variant's small block
    pixel_block = max(1, int(os.environ.get("DEXIRAFT_PALLAS_PIXEL_BLOCK",
                                            _PIXEL_BLOCK)))
    n = h * w
    n_pad = (-n) % pixel_block
    np_tot = n + n_pad
    flat = lambda a, d: jnp.pad(a.reshape(b, n, *a.shape[3:]),
                                ((0, 0), (0, n_pad)) + ((0, 0),) * d)

    f1_flat = flat(fmap1.astype(jnp.float32), 1)

    grid = (b, np_tot // pixel_block)
    smem_spec = pl.BlockSpec((1, pixel_block), lambda bi, ti: (bi, ti),
                             memory_space=pltpu.SMEM)
    frac_spec = pl.BlockSpec((1, pixel_block, 2), lambda bi, ti: (bi, ti, 0),
                             memory_space=pltpu.VMEM)
    f1_spec = pl.BlockSpec((1, pixel_block, c), lambda bi, ti: (bi, ti, 0),
                           memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((num_levels * win * win, feat),
                          lambda bi, ti: (0, 0), memory_space=pltpu.VMEM)
    b_spec = pl.BlockSpec((1, feat), lambda bi, ti: (0, 0),
                          memory_space=pltpu.VMEM)

    inputs = [f1_flat, weight.astype(jnp.float32),
              bias.reshape(1, feat).astype(jnp.float32)]
    in_specs = [f1_spec, w_spec, b_spec]
    for lvl, f2 in enumerate(fmap2_levels):
        h2, w2 = level_shapes[lvl]
        sx, sy, frac = _index_prep(coords / (2.0 ** lvl), h2, w2, r)
        # pad each level in its STORAGE dtype — the quantized bytes are
        # what stream HBM->VMEM
        f2p = jnp.pad(f2, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        inputs += [flat(sx, 0), flat(sy, 0), flat(frac, 1), f2p]
        in_specs += [
            smem_spec, smem_spec, frac_spec,
            pl.BlockSpec((1, h2 + 2 * pad, w2 + 2 * pad, c),
                         lambda bi, ti: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ]

    kernel = functools.partial(_fused_kernel, radius=r,
                               num_levels=num_levels,
                               level_shapes=level_shapes)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, pixel_block, feat),
                               lambda bi, ti: (bi, ti, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, np_tot, feat), jnp.float32),
        scratch_shapes=[pltpu.VMEM((pixel_block, k * k), jnp.float32)],
        interpret=interpret,
    )(*inputs)
    return out[:, :n].reshape(b, h, w, feat)


def fused_reference(fmap1, fmap2_levels, coords, weight, bias, radius,
                    row_chunk=None):
    """The unfused XLA formulation of the fused kernel — per-level
    local_corr_level windows concatenated, then the 1x1 conv as a plain
    contraction. The parity/gradient reference AND the backward-pass
    recompute target of pallas_fused_step (the same split as
    pallas_local_corr_level's VJP: hand-written forward kernel, XLA
    matmul backward).

    ``weight`` is (L*win^2, F) with any per-level dequantization scales
    already folded in (the caller's job — FusedCorrEncoder); levels may
    be stored bf16/int8, upcast here exactly as the kernel upcasts.
    """
    b, h, w, _ = fmap1.shape
    outs = []
    for lvl, f2 in enumerate(fmap2_levels):
        outs.append(local_corr_level(
            fmap1, f2.astype(jnp.float32), coords / (2.0 ** lvl), radius,
            row_chunk=row_chunk))
    corr = jnp.concatenate(outs, axis=-1)  # (B, H, W, L*win^2)
    return (jnp.einsum("bhwc,cf->bhwf", corr, weight.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
            + bias.astype(jnp.float32))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def pallas_fused_step(fmap1, fmap2_levels, coords, weight, bias,
                      radius: int, interpret=None, row_chunk=8):
    """Fused lookup+update-entry: (B,H,W,C) x L levels x level-0 coords x
    (L*(2r+1)^2, F) weight x (F,) bias -> (B,H,W,F).

    One Pallas call per refinement iteration: the full multi-level window
    lookup feeds the motion encoder's 1x1 corr conv while each pixel
    block's patches are VMEM-resident (see module comment). interpret=None
    defers to DEXIRAFT_PALLAS_INTERPRET; row_chunk bounds the backward
    recompute's transient buffer like the per-level kernel's VJP.

    Gradients flow to fmap1, float-dtype fmap2 levels, weight, and bias
    by recomputing through fused_reference; coords get zero gradient
    (the CUDA-kernel semantics shared by every corr path). int8-stored
    levels are non-differentiable by construction (their float0
    cotangent falls out of jax.vjp) — the model layer refuses to train
    int8 pyramids rather than training with dead fmap2 gradients.
    """
    return _fused_forward(fmap1, tuple(fmap2_levels), coords, weight, bias,
                          radius, interpret)


def _fused_fwd(fmap1, fmap2_levels, coords, weight, bias, radius, interpret,
               row_chunk):
    out = _fused_forward(fmap1, tuple(fmap2_levels), coords, weight, bias,
                         radius, interpret)
    return out, (fmap1, tuple(fmap2_levels), coords, weight, bias)


def _fused_bwd(radius, interpret, row_chunk, res, g):
    fmap1, fmap2_levels, coords, weight, bias = res
    _, vjp = jax.vjp(
        lambda f1, f2s, w_, b_: fused_reference(
            f1, f2s, coords, w_, b_, radius, row_chunk=row_chunk),
        fmap1, fmap2_levels, weight, bias)
    g1, g2s, gw, gb = vjp(g)
    return g1, g2s, jnp.zeros_like(coords), gw, gb


pallas_fused_step.defvjp(_fused_fwd, _fused_bwd)
