"""Pallas TPU kernel for the local correlation lookup.

The tpu-native twin of alt_cuda_corr/correlation_kernel.cu:19-119, in the
gather formulation (SURVEY.md §2.2): the CUDA kernel stages fmap tiles
through __shared__ memory and scatter-accumulates bilinear corner weights;
here the (zero-padded) fmap2 level lives in VMEM, each grid step owns a
block of P query pixels, and per pixel we

  1. dynamic-slice the (2r+2, 2r+2, C) integer patch around floor(coords)
     (VMEM load driven by SMEM-resident scalar indices),
  2. dot against the pixel's fmap1 row on the VPU (fp32 accumulate),
  3. mask out-of-frame lattice points (zero-padding semantics of
     bilinear_sampler / F.grid_sample(zeros)),

then blend the 4 bilinear corners vectorized over the whole block.

Index preparation happens in XLA: coords are clipped to [-r-1, size+r]
(out-of-range windows are provably all-zero there because the clip bounds
are integers, so the +1 corner weight vanishes at the boundary), and fmap2
is zero-padded by 2r+2 so every clipped window is a legal static-size
slice.

Gradients: forward-only kernel wrapped in jax.custom_vjp; the VJP
recomputes through the XLA gather formulation (local_corr_level), giving
fmap gradients and zero coords gradient — the CUDA backward's semantics
(correlation_kernel.cu:307) without a second hand-written kernel.

Three kernel generations live here, newest last:
  * the per-pixel slice kernels (corr_impl="pallas"): gather-shaped
    per-query dynamic slices, whole padded fmap2 levels staged in VMEM;
  * the fused per-pixel step (pallas_fused_step): the same lattice
    machinery plus the motion encoder's 1x1 corr conv in-kernel, with a
    VMEM-budget split path at large fp32 geometries;
  * the flash-blocked kernels (corr_impl="flash" —
    flash_local_corr_level / flash_fused_step): fmap2 stays in HBM and
    is row-block-streamed per fmap1 pixel block, the partial correlation
    is a block x blockᵀ MXU matmul windowed in-register by the hat
    matrices, and there is no budget split at any geometry. See the
    "Flash-blocked kernel" section below.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dexiraft_tpu.ops.local_corr import local_corr_level

# queries per grid step; read through _pixel_block() so on-chip tuning
# (scripts/tpu_smoke.py sweeps DEXIRAFT_PALLAS_PIXEL_BLOCK) needs no
# code edit. Resolved at trace time — rebuild the jit to change it.
_PIXEL_BLOCK = 256


def _pixel_block() -> int:
    # the batched variant stages (P, k, k, C) fp32 patches in VMEM
    # (~100 KiB per pixel at C=256, r=4), so its default block must be
    # much smaller than the loop kernel's
    default = 32 if _variant() == "batched" else _PIXEL_BLOCK
    # clamp: a bad flag must fail soft, not as a ZeroDivisionError deep
    # inside jit tracing
    return max(1, int(os.environ.get("DEXIRAFT_PALLAS_PIXEL_BLOCK",
                                     default)))


def _interpret_default() -> bool:
    # DEXIRAFT_PALLAS_INTERPRET=1 runs the kernel in interpreter mode
    # (trace-time switch) — lets the whole-model corr_impl="pallas" path
    # run off-chip (tests/test_local_corr.py). Never set it on a TPU
    # host: the interpreter is orders of magnitude slower.
    return os.environ.get("DEXIRAFT_PALLAS_INTERPRET", "0") == "1"


def _variant() -> str:
    # "loop": the original per-pixel slice+reduce kernel.
    # "batched": per-pixel work reduced to a pure patch COPY into a
    # (P, k, k, C) scratch, then ONE vectorized multiply-reduce over the
    # whole block — the shape the VPU pipelines well (the per-pixel
    # (k,k,C) reduce of "loop" is latency-bound, VERDICT r4 weak-6).
    # Costs P*k*k*C*4 B of extra VMEM, so "batched" wants a SMALLER
    # pixel block (default 32 vs 256). Trace-time switch; the on-chip
    # A/B lives in scripts/tpu_smoke.py.
    v = os.environ.get("DEXIRAFT_PALLAS_VARIANT", "loop")
    return v if v in ("loop", "batched") else "loop"


def _blend_corners_val(lattice, frac_ref):
    """Bilinear-blend the (P, k, k) integer-lattice dots into a
    (P, win*win) window value, x offset on the slow axis (the reference
    channel order — ops.corr)."""
    p_block, k, _ = lattice.shape
    win = k - 1
    fx = frac_ref[0, :, 0].reshape(p_block, 1, 1)
    fy = frac_ref[0, :, 1].reshape(p_block, 1, 1)
    tl = lattice[:, 0:win, 0:win]
    tr = lattice[:, 0:win, 1:win + 1]
    bl = lattice[:, 1:win + 1, 0:win]
    br = lattice[:, 1:win + 1, 1:win + 1]
    out = ((1 - fy) * (1 - fx) * tl + (1 - fy) * fx * tr
           + fy * (1 - fx) * bl + fy * fx * br)
    return out.swapaxes(1, 2).reshape(p_block, win * win)


def _blend_corners(lattice, frac_ref, out_ref):
    out_ref[0] = _blend_corners_val(lattice, frac_ref)


def _corr_kernel_batched(sx_ref, sy_ref, f1_ref, f2_ref, frac_ref,
                         sxv_ref, syv_ref, out_ref, patches_ref,
                         *, radius: int, h2: int, w2: int):
    r = radius
    k = 2 * r + 2
    p_block = f1_ref.shape[1]
    c = f1_ref.shape[2]
    inv_sqrt_c = 1.0 / (c ** 0.5)

    # phase 1: pure data movement — stage every pixel's (k, k, C) patch
    # into the block scratch; no per-pixel compute on the critical path
    def body(p, _):
        sx = sx_ref[0, p]
        sy = sy_ref[0, p]
        patches_ref[pl.ds(p, 1)] = (
            f2_ref[0, pl.ds(sy, k), pl.ds(sx, k), :].astype(jnp.float32)[None])
        return 0

    jax.lax.fori_loop(0, p_block, body, 0)

    # phase 2: ONE vectorized multiply-reduce over the whole block
    patches = patches_ref[:].astype(jnp.float32)          # (P, k, k, C)
    f1 = f1_ref[0].astype(jnp.float32)                    # (P, C)
    dots = jnp.sum(patches * f1[:, None, None, :], axis=3)  # (P, k, k)

    # vectorized out-of-frame mask: true lattice origin per pixel is
    # (sx - (r + 2), sy - (r + 2)) — see the loop kernel's derivation
    sxv = sxv_ref[0]                                      # (P,) int32
    syv = syv_ref[0]
    gx = (jax.lax.broadcasted_iota(jnp.int32, (p_block, k, k), 2)
          + (sxv - 2 - 2 * r)[:, None, None])
    gy = (jax.lax.broadcasted_iota(jnp.int32, (p_block, k, k), 1)
          + (syv - 2 - 2 * r)[:, None, None])
    valid = (gx >= 0) & (gx < w2) & (gy >= 0) & (gy < h2)
    dots = jnp.where(valid, dots * inv_sqrt_c, 0.0)
    _blend_corners(dots, frac_ref, out_ref)


def _fill_lattice_dots(sx_ref, sy_ref, f1_ref, f2_ref, lattice_ref,
                       *, radius: int, h2: int, w2: int):
    """Per-pixel slice+dot+mask loop shared by the per-level loop kernel
    and the fused kernel: stage each pixel's (k, k) integer-lattice dots
    (fp32 accumulate, storage dtype upcast in-register) into lattice_ref.

    Masking: lattice points outside the ORIGINAL (unpadded) frame read
    zero; slice starts were clipped into the padded frame, so the true
    lattice origin is recomputed as x0 = sx - (r + 2), y0 = sy - (r + 2).
    """
    r = radius
    k = 2 * r + 2
    p_block = f1_ref.shape[1]
    c = f1_ref.shape[2]
    inv_sqrt_c = 1.0 / (c ** 0.5)

    def body(p, _):
        sx = sx_ref[0, p]
        sy = sy_ref[0, p]
        patch = f2_ref[0, pl.ds(sy, k), pl.ds(sx, k), :]  # (k, k, C)
        f1p = f1_ref[0, p, :]  # (C,)
        dots = jnp.sum(
            patch.astype(jnp.float32) * f1p.astype(jnp.float32)[None, None, :],
            axis=2,
        )  # (k, k)
        gx = jax.lax.broadcasted_iota(jnp.int32, (k, k), 1) + (sx - 2 - 2 * r)
        gy = jax.lax.broadcasted_iota(jnp.int32, (k, k), 0) + (sy - 2 - 2 * r)
        valid = ((gx >= 0) & (gx < w2) & (gy >= 0) & (gy < h2))
        dots = jnp.where(valid, dots * inv_sqrt_c, 0.0)
        lattice_ref[p, :] = dots.reshape(k * k)
        return 0

    jax.lax.fori_loop(0, p_block, body, 0)


def _corr_kernel(sx_ref, sy_ref, f1_ref, f2_ref, frac_ref, out_ref,
                 lattice_ref, *, radius: int, h2: int, w2: int):
    k = 2 * radius + 2
    p_block = f1_ref.shape[1]
    _fill_lattice_dots(sx_ref, sy_ref, f1_ref, f2_ref, lattice_ref,
                       radius=radius, h2=h2, w2=w2)
    _blend_corners(lattice_ref[:].reshape(p_block, k, k), frac_ref, out_ref)


def _pallas_forward(fmap1: jax.Array, fmap2: jax.Array, coords: jax.Array,
                    radius: int, interpret=None) -> jax.Array:
    if interpret is None:
        interpret = _interpret_default()
    b, h, w, c = fmap1.shape
    h2, w2 = fmap2.shape[1:3]
    r = radius
    k = 2 * r + 2
    win = 2 * r + 1
    pad = k  # 2r+2 zeros on every side

    # ---- XLA-side index prep (shared with the fused kernel; slice
    # start in the padded frame is x0 - r + pad = x0 + r + 2, in range
    # [1, w2 + 2r + 2] given the clip — always a legal k-slice) ----
    sx, sy, frac = _index_prep(coords, h2, w2, r)

    # pad in the STORAGE dtype (fp32/bf16/int8 — ops/quant.py): the
    # quantized bytes are what stream HBM->VMEM; the kernel upcasts each
    # patch in-register (patch.astype(f32) in the dot)
    f2p = jnp.pad(fmap2, ((0, 0), (pad, pad), (pad, pad), (0, 0)))

    # flatten pixels, pad to the block size
    pixel_block = _pixel_block()
    n = h * w
    n_pad = (-n) % pixel_block
    np_tot = n + n_pad
    flat = lambda a, d: jnp.pad(a.reshape(b, n, *a.shape[3:]),
                                ((0, 0), (0, n_pad)) + ((0, 0),) * d)
    f1_flat = flat(fmap1.astype(jnp.float32), 1)
    sx_flat = flat(sx, 0)  # padded pixels read slice start 0 — harmless
    sy_flat = flat(sy, 0)
    frac_flat = flat(frac, 1)

    grid = (b, np_tot // pixel_block)
    smem_spec = pl.BlockSpec((1, pixel_block), lambda bi, ti: (bi, ti),
                             memory_space=pltpu.SMEM)
    vmem_vec_spec = pl.BlockSpec((1, pixel_block), lambda bi, ti: (bi, ti),
                                 memory_space=pltpu.VMEM)
    f1_spec = pl.BlockSpec((1, pixel_block, c), lambda bi, ti: (bi, ti, 0),
                           memory_space=pltpu.VMEM)
    f2_spec = pl.BlockSpec((1, h2 + 2 * pad, w2 + 2 * pad, c),
                           lambda bi, ti: (bi, 0, 0, 0),
                           memory_space=pltpu.VMEM)
    frac_spec = pl.BlockSpec((1, pixel_block, 2), lambda bi, ti: (bi, ti, 0),
                             memory_space=pltpu.VMEM)
    out_specs = pl.BlockSpec((1, pixel_block, win * win),
                             lambda bi, ti: (bi, ti, 0),
                             memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((b, np_tot, win * win), jnp.float32)

    if _variant() == "batched":
        kernel = functools.partial(_corr_kernel_batched, radius=r,
                                   h2=h2, w2=w2)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            # slice starts twice: SMEM scalars drive the dynamic patch
            # slices, VMEM vectors feed the vectorized lattice mask
            in_specs=[smem_spec, smem_spec, f1_spec, f2_spec, frac_spec,
                      vmem_vec_spec, vmem_vec_spec],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((pixel_block, k, k, c), jnp.float32)],
            interpret=interpret,
        )(sx_flat, sy_flat, f1_flat, f2p, frac_flat, sx_flat, sy_flat)
    else:
        kernel = functools.partial(_corr_kernel, radius=r, h2=h2, w2=w2)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[smem_spec, smem_spec, f1_spec, f2_spec, frac_spec],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((pixel_block, k * k), jnp.float32)],
            interpret=interpret,
        )(sx_flat, sy_flat, f1_flat, f2p, frac_flat)

    return out[:, :n].reshape(b, h, w, win * win)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_local_corr_level(fmap1, fmap2, coords, radius: int,
                            interpret=None, row_chunk=8):
    """(B,H,W,C) x (B,H2,W2,C) x (B,H,W,2 level coords) -> (B,H,W,(2r+1)^2).

    interpret=None defers to DEXIRAFT_PALLAS_INTERPRET (off-chip debug
    switch, resolved at trace time). row_chunk only affects the backward
    recompute (the forward kernel is already pixel-blocked); pass the
    model's corr_row_chunk so the VJP's transient patch buffer honors
    the same bound.
    """
    return _pallas_forward(fmap1, fmap2, coords, radius, interpret)


def _fwd(fmap1, fmap2, coords, radius, interpret, row_chunk):
    return (_pallas_forward(fmap1, fmap2, coords, radius, interpret),
            (fmap1, fmap2, coords))


def _bwd(radius, interpret, row_chunk, res, g):
    fmap1, fmap2, coords = res
    # row-chunked recompute: bounds the backward's transient patch buffer
    # the same way the forward XLA path does
    _, vjp = jax.vjp(
        lambda f1, f2: local_corr_level(f1, f2, coords, radius,
                                        row_chunk=row_chunk),
        fmap1, fmap2)
    g1, g2 = vjp(g)
    return g1, g2, jnp.zeros_like(coords)


pallas_local_corr_level.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# Fused refinement-step kernel: 4-level lookup + motion-encoder entry
# ---------------------------------------------------------------------------
#
# The per-level kernel above still writes each level's (B, H, W, win^2)
# window to HBM, where XLA's motion encoder reads the concatenated
# (B, H, W, L*win^2) tensor back for its 1x1 corr conv — two full HBM
# round-trips of the widest activation in the refinement loop. The fused
# kernel does the whole chain in ONE pallas_call per iteration: every
# pyramid level's window is computed while the pixel block's patches are
# VMEM-resident and immediately contracted against that level's slice of
# the motion encoder's 1x1 conv weight (an MXU matmul), so only the
# (B, H, W, F) conv OUTPUT ever touches HBM. F=256 vs L*win^2=324 plus
# the per-level intermediates: the loop's widest tensors never leave
# VMEM. Division of labor for the linear factors: the kernel applies
# 1/sqrt(C) itself (inside _fill_lattice_dots, same as the per-level
# kernel — do NOT fold it into the weights too); the caller folds ONLY
# the per-level int8 dequantization scales into the weight slices
# (models/update.py FusedCorrEncoder). The kernel reads the pyramid in
# its storage dtype (fp32/bf16/int8) and upcasts in-register.


def _fused_kernel(*refs, radius: int, num_levels: int, level_shapes: tuple):
    """refs: f1, w, b, then [sx, sy, frac, f2p] per level, out, lattice.

    Per level: the per-pixel patch slice+dot of _corr_kernel, the corner
    blend, then window @ w_level accumulated into the block's (P, F)
    output — all while resident in VMEM.
    """
    f1_ref, w_ref, b_ref = refs[0], refs[1], refs[2]
    lvl_refs = refs[3:3 + 4 * num_levels]
    out_ref, lattice_ref = refs[3 + 4 * num_levels], refs[4 + 4 * num_levels]

    r = radius
    k = 2 * r + 2
    win = 2 * r + 1
    p_block = f1_ref.shape[1]

    acc = jnp.broadcast_to(b_ref[0].astype(jnp.float32),
                           (p_block, b_ref.shape[1]))
    for lvl in range(num_levels):
        sx_ref, sy_ref, frac_ref, f2_ref = lvl_refs[4 * lvl:4 * lvl + 4]
        h2, w2 = level_shapes[lvl]
        # same per-pixel slice+dot+mask as the per-level loop kernel
        # (shared helper — ONE copy of the lattice-origin arithmetic)
        _fill_lattice_dots(sx_ref, sy_ref, f1_ref, f2_ref, lattice_ref,
                           radius=r, h2=h2, w2=w2)
        window = _blend_corners_val(
            lattice_ref[:].reshape(p_block, k, k), frac_ref)  # (P, win^2)
        w_lvl = w_ref[pl.ds(lvl * win * win, win * win), :]
        acc = acc + jnp.dot(window, w_lvl.astype(jnp.float32),
                            preferred_element_type=jnp.float32)
    out_ref[0] = acc


def _index_prep(coords: jax.Array, h2: int, w2: int, radius: int):
    """XLA-side index prep for one level (the same clip/floor/frac as
    _pallas_forward, at this level's geometry)."""
    r = radius
    x = jnp.clip(coords[..., 0].astype(jnp.float32),
                 -(r + 1.0), w2 - 1 + r + 1.0)
    y = jnp.clip(coords[..., 1].astype(jnp.float32),
                 -(r + 1.0), h2 - 1 + r + 1.0)
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    frac = jnp.stack([x - x0, y - y0], axis=-1)
    sx = x0.astype(jnp.int32) + (r + 2)
    sy = y0.astype(jnp.int32) + (r + 2)
    return sx, sy, frac


# combined VMEM budget for the padded fmap2 levels a single fused call
# may stage (bytes). ~16 MiB/core total minus the f1/weight/out/lattice
# blocks and double-buffering headroom. At the 440x1024 eval geometry the
# four padded fp32 levels need ~18 MB — over budget — so the fp32 fused
# path splits into per-level fused calls (each holds ONE level, the
# footprint the per-level kernel already proves fits); bf16 (~9 MB) and
# int8 (~4.5 MB) stay single-call, which is the configuration the fused
# kernel exists for. The env override is parsed ONCE at module load
# (tests override the module constant, not the environment).
_FUSED_LEVELS_VMEM_DEFAULT = 12 * 1024 * 1024


def _parse_positive_int_env(name: str, default: int) -> int:
    """Parse an integer-bytes env override once, at module load, with an
    actionable refusal instead of a bare ValueError from int()."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r} is not an integer; set a byte count "
            f"(e.g. {default} = {default // 2**20} MiB) or unset it"
        ) from None
    if value <= 0:
        raise ValueError(
            f"{name}={raw!r} must be a positive byte count; the VMEM "
            f"budget bounds the fmap2 levels one fused call stages "
            f"(default {default})")
    return value


_FUSED_LEVELS_VMEM_BYTES = _parse_positive_int_env(
    "DEXIRAFT_FUSED_LEVELS_VMEM_BYTES", _FUSED_LEVELS_VMEM_DEFAULT)


def _fused_levels_budget() -> int:
    return _FUSED_LEVELS_VMEM_BYTES


def _fused_forward(fmap1: jax.Array, fmap2_levels: tuple, coords: jax.Array,
                   weight: jax.Array, bias: jax.Array, radius: int,
                   interpret=None) -> jax.Array:
    if interpret is None:
        interpret = _interpret_default()
    b, h, w, c = fmap1.shape
    r = radius
    k = 2 * r + 2
    win = 2 * r + 1
    pad = k
    num_levels = len(fmap2_levels)
    feat = weight.shape[1]
    level_shapes = tuple(f2.shape[1:3] for f2 in fmap2_levels)

    if num_levels > 1:
        staged = sum((h2 + 2 * pad) * (w2 + 2 * pad) * c * f2.dtype.itemsize
                     for (h2, w2), f2 in zip(level_shapes, fmap2_levels))
        if staged > _fused_levels_budget():
            # over the VMEM budget (fp32 pyramid at large geometry):
            # one fused lookup+conv call PER level — each stages a single
            # level, still contracting its window against the weight
            # slice in-kernel, and the (B, H, W, win^2) per-level corr
            # features still never materialize; only L partial (B,H,W,F)
            # products are summed in XLA. Exactly linear, so identical
            # to the single-call result up to summation order.
            ww = win * win
            out = None
            zero_bias = jnp.zeros_like(bias)
            for lvl in range(num_levels):
                o = _fused_forward(
                    fmap1, (fmap2_levels[lvl],), coords / (2.0 ** lvl),
                    weight[lvl * ww:(lvl + 1) * ww], zero_bias, radius,
                    interpret)
                out = o if out is None else out + o
            return out + bias.astype(jnp.float32)

    # the fused kernel has the loop kernel's VMEM shape (one (P, k*k)
    # lattice scratch), so it shares the loop default — not the batched
    # variant's small block
    pixel_block = max(1, int(os.environ.get("DEXIRAFT_PALLAS_PIXEL_BLOCK",
                                            _PIXEL_BLOCK)))
    n = h * w
    n_pad = (-n) % pixel_block
    np_tot = n + n_pad
    flat = lambda a, d: jnp.pad(a.reshape(b, n, *a.shape[3:]),
                                ((0, 0), (0, n_pad)) + ((0, 0),) * d)

    f1_flat = flat(fmap1.astype(jnp.float32), 1)

    grid = (b, np_tot // pixel_block)
    smem_spec = pl.BlockSpec((1, pixel_block), lambda bi, ti: (bi, ti),
                             memory_space=pltpu.SMEM)
    frac_spec = pl.BlockSpec((1, pixel_block, 2), lambda bi, ti: (bi, ti, 0),
                             memory_space=pltpu.VMEM)
    f1_spec = pl.BlockSpec((1, pixel_block, c), lambda bi, ti: (bi, ti, 0),
                           memory_space=pltpu.VMEM)
    w_spec = pl.BlockSpec((num_levels * win * win, feat),
                          lambda bi, ti: (0, 0), memory_space=pltpu.VMEM)
    b_spec = pl.BlockSpec((1, feat), lambda bi, ti: (0, 0),
                          memory_space=pltpu.VMEM)

    inputs = [f1_flat, weight.astype(jnp.float32),
              bias.reshape(1, feat).astype(jnp.float32)]
    in_specs = [f1_spec, w_spec, b_spec]
    for lvl, f2 in enumerate(fmap2_levels):
        h2, w2 = level_shapes[lvl]
        sx, sy, frac = _index_prep(coords / (2.0 ** lvl), h2, w2, r)
        # pad each level in its STORAGE dtype — the quantized bytes are
        # what stream HBM->VMEM
        f2p = jnp.pad(f2, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
        inputs += [flat(sx, 0), flat(sy, 0), flat(frac, 1), f2p]
        in_specs += [
            smem_spec, smem_spec, frac_spec,
            pl.BlockSpec((1, h2 + 2 * pad, w2 + 2 * pad, c),
                         lambda bi, ti: (bi, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ]

    kernel = functools.partial(_fused_kernel, radius=r,
                               num_levels=num_levels,
                               level_shapes=level_shapes)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, pixel_block, feat),
                               lambda bi, ti: (bi, ti, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, np_tot, feat), jnp.float32),
        scratch_shapes=[pltpu.VMEM((pixel_block, k * k), jnp.float32)],
        interpret=interpret,
    )(*inputs)
    return out[:, :n].reshape(b, h, w, feat)


def fused_reference(fmap1, fmap2_levels, coords, weight, bias, radius,
                    row_chunk=None):
    """The unfused XLA formulation of the fused kernel — per-level
    local_corr_level windows concatenated, then the 1x1 conv as a plain
    contraction. The parity/gradient reference AND the backward-pass
    recompute target of pallas_fused_step (the same split as
    pallas_local_corr_level's VJP: hand-written forward kernel, XLA
    matmul backward).

    ``weight`` is (L*win^2, F) with any per-level dequantization scales
    already folded in (the caller's job — FusedCorrEncoder); levels may
    be stored bf16/int8, upcast here exactly as the kernel upcasts.
    """
    b, h, w, _ = fmap1.shape
    outs = []
    for lvl, f2 in enumerate(fmap2_levels):
        outs.append(local_corr_level(
            fmap1, f2.astype(jnp.float32), coords / (2.0 ** lvl), radius,
            row_chunk=row_chunk))
    corr = jnp.concatenate(outs, axis=-1)  # (B, H, W, L*win^2)
    return (jnp.einsum("bhwc,cf->bhwf", corr, weight.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
            + bias.astype(jnp.float32))


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def pallas_fused_step(fmap1, fmap2_levels, coords, weight, bias,
                      radius: int, interpret=None, row_chunk=8):
    """Fused lookup+update-entry: (B,H,W,C) x L levels x level-0 coords x
    (L*(2r+1)^2, F) weight x (F,) bias -> (B,H,W,F).

    One Pallas call per refinement iteration: the full multi-level window
    lookup feeds the motion encoder's 1x1 corr conv while each pixel
    block's patches are VMEM-resident (see module comment). interpret=None
    defers to DEXIRAFT_PALLAS_INTERPRET; row_chunk bounds the backward
    recompute's transient buffer like the per-level kernel's VJP.

    Gradients flow to fmap1, float-dtype fmap2 levels, weight, and bias
    by recomputing through fused_reference; coords get zero gradient
    (the CUDA-kernel semantics shared by every corr path). int8-stored
    levels are non-differentiable by construction (their float0
    cotangent falls out of jax.vjp) — the model layer refuses to train
    int8 pyramids rather than training with dead fmap2 gradients.
    """
    return _fused_forward(fmap1, tuple(fmap2_levels), coords, weight, bias,
                          radius, interpret)


def _fused_fwd(fmap1, fmap2_levels, coords, weight, bias, radius, interpret,
               row_chunk):
    out = _fused_forward(fmap1, tuple(fmap2_levels), coords, weight, bias,
                         radius, interpret)
    return out, (fmap1, tuple(fmap2_levels), coords, weight, bias)


def _fused_bwd(radius, interpret, row_chunk, res, g):
    fmap1, fmap2_levels, coords, weight, bias = res
    _, vjp = jax.vjp(
        lambda f1, f2s, w_, b_: fused_reference(
            f1, f2s, coords, w_, b_, radius, row_chunk=row_chunk),
        fmap1, fmap2_levels, weight, bias)
    g1, g2s, gw, gb = vjp(g)
    return g1, g2s, jnp.zeros_like(coords), gw, gb


pallas_fused_step.defvjp(_fused_fwd, _fused_bwd)


# ---------------------------------------------------------------------------
# Flash-blocked kernel: the materialized-volume killer (ISSUE 12)
# ---------------------------------------------------------------------------
#
# The per-pixel kernels above are gather-shaped (one (k, k, C) dynamic
# slice + VPU reduce per query) and must stage whole padded fmap2 levels
# in VMEM, which is why _fused_forward splits into per-level calls when
# the fp32 pyramid blows the budget. The flash-blocked kernel is the
# flash-attention translation of alt_cuda_corr instead: fmap2 levels
# STAY IN HBM (memory_space=ANY); per fmap1 pixel block the kernel DMAs
# VMEM-sized row blocks of each level, computes the partial all-pairs
# correlation as ONE block x blockᵀ MXU matmul (the exact formulation
# ops/local_corr.py proves correct in XLA), windows it in-register with
# the separable triangular hat matrices of ops.corr._axis_interp_matrix
# (bilinear blend + out-of-frame zeroing in one expression — no corner
# blending, no coordinate clipping), and accumulates. Row blocks whose
# rows cannot intersect any query window in the block (hat support is
# empty outside [ty - r - 1, ty + r + 1]) are skipped before the DMA,
# so HBM traffic tracks the windows actually needed, not H2 x W2.
#
# Consequences: VMEM use is O(pixel_block) at ANY geometry (no budget
# split path), HBM holds only the fmaps (never a volume, never padded
# per-level copies — levels are padded only to a row-block multiple),
# and there is ONE kernel per refinement iteration. The fused variant
# additionally contracts each level's window against the motion
# encoder's weight slice in-kernel (same contract as _fused_kernel: the
# kernel applies 1/sqrt(C) itself, the caller folds only int8 scales
# into the weights); the unfused variant writes the (P, L*win^2) window
# features — the flash lookup for corr_impl="flash" without
# fused_update.

# queries per flash grid step / fmap2 rows per DMA block. Trace-time
# env knobs like DEXIRAFT_PALLAS_PIXEL_BLOCK; the defaults bound the
# resident set to ~4 MB at C=256 (f1 block 256 KB + one (8, W2, C)
# row block + the (P, rows*W2) dots transient).
_FLASH_PIXEL_BLOCK = 256
_FLASH_ROWS = 8


def _flash_pixel_block() -> int:
    return max(1, int(os.environ.get("DEXIRAFT_FLASH_PIXEL_BLOCK",
                                     _FLASH_PIXEL_BLOCK)))


def _flash_rows() -> int:
    return max(1, int(os.environ.get("DEXIRAFT_FLASH_ROWS", _FLASH_ROWS)))


def _hat(taps_center, length, offset, radius, p_block):
    """(P,) centers -> (P, 2r+1, length) triangular hat weights for axis
    positions offset..offset+length-1 — the in-kernel twin of
    ops.corr._axis_interp_matrix(center, radius, length, offset):
    A[p, j, q] = relu(1 - |(offset + q) - (center_p + j - r)|). Out-of-
    range taps have empty support, reproducing bilinear_sampler's zero
    padding; zero-padded rows/cols get weights but multiply zeros."""
    win = 2 * radius + 1
    pos = offset + jax.lax.broadcasted_iota(
        jnp.float32, (p_block, win, length), 2)
    tap = (taps_center[:, None, None]
           + jax.lax.broadcasted_iota(jnp.float32, (p_block, win, length), 1)
           - radius)
    return jnp.maximum(0.0, 1.0 - jnp.abs(pos - tap))


def _flash_kernel(*refs, radius: int, level_ids: tuple, level_shapes: tuple,
                  num_levels_total: int, rows: int, fused: bool):
    """refs: f1, coords, [w, b], f2 level refs (ANY/HBM), out, then
    scratch: f2 row-block buffer, window accumulator, [out accumulator],
    DMA semaphore.

    ``level_ids`` are the ORIGINAL pyramid indices of the staged levels
    (degenerate 0-row tail levels are filtered out on the XLA side —
    their windows are identically zero); ``num_levels_total`` sizes the
    unfused output / weight slicing in original-pyramid channels."""
    n_lvls = len(level_ids)
    if fused:
        f1_ref, coords_ref, w_ref, b_ref = refs[:4]
        lvl_refs = refs[4:4 + n_lvls]
        out_ref = refs[4 + n_lvls]
        f2blk_ref, win_ref, acc_ref, sem = refs[5 + n_lvls:]
    else:
        f1_ref, coords_ref = refs[:2]
        lvl_refs = refs[2:2 + n_lvls]
        out_ref = refs[2 + n_lvls]
        f2blk_ref, win_ref, sem = refs[3 + n_lvls:]

    r = radius
    win = 2 * r + 1
    p_block = f1_ref.shape[1]
    c = f1_ref.shape[2]
    bi = pl.program_id(0)

    # fold the 1/sqrt(C) normalization into the query block once — every
    # dots matmul below then carries it (linear), same division of labor
    # as the per-pixel kernels (the caller never folds it into weights)
    f1 = f1_ref[0].astype(jnp.float32) * (1.0 / (c ** 0.5))
    if fused:
        acc_ref[...] = jnp.broadcast_to(b_ref[0].astype(jnp.float32),
                                        (p_block, b_ref.shape[1]))
    elif n_lvls < num_levels_total:
        # filtered degenerate levels own output channels nobody writes —
        # zero the whole block once so they read as the zero windows
        # they are
        out_ref[0] = jnp.zeros(
            (p_block, num_levels_total * win * win), jnp.float32)

    for f2_ref, lvl, (h2, w2) in zip(lvl_refs, level_ids, level_shapes):
        n_blocks = f2_ref.shape[1] // rows
        inv = 1.0 / (2.0 ** lvl)
        tx = coords_ref[0, :, 0].astype(jnp.float32) * inv  # (P,)
        ty = coords_ref[0, :, 1].astype(jnp.float32) * inv
        # x hats cover the whole level width (a row of queries spans it);
        # y hats are built per row block inside the loop
        ax = _hat(tx, w2, 0, r, p_block)  # (P, win, w2)
        # hat support of tap t is (t-1, t+1); taps span [ty-r, ty+r] —
        # a row block outside [min ty - r - 1, max ty + r + 1] cannot
        # contribute, so its DMA and matmuls are skipped entirely
        t_lo = jnp.min(ty) - (r + 1)
        t_hi = jnp.max(ty) + (r + 1)
        win_ref[...] = jnp.zeros_like(win_ref)

        def body(blk_i, _, f2_ref=f2_ref, ax=ax, ty=ty,
                 t_lo=t_lo, t_hi=t_hi, w2=w2):
            row0 = blk_i * rows

            @pl.when((row0 <= t_hi) & (row0 + rows - 1 >= t_lo))
            def _():
                dma = pltpu.make_async_copy(
                    f2_ref.at[bi, pl.ds(row0, rows)],
                    f2blk_ref.at[:, :w2, :], sem)
                dma.start()
                dma.wait()
                blk = (f2blk_ref[:, :w2, :]
                       .reshape(rows * w2, c).astype(jnp.float32))
                # partial all-pairs block: (P, C) x (rows*w2, C)ᵀ on the
                # MXU — the local_corr formulation, never materialized
                # beyond this row block
                dots = jax.lax.dot_general(
                    f1, blk, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32)
                dots = dots.reshape(p_block, rows, w2)
                ay = _hat(ty, rows, row0, r, p_block)  # (P, win, rows)
                rows_c = jax.lax.dot_general(  # (P, win_y, w2)
                    ay, dots, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)
                wp = jax.lax.dot_general(  # (P, win_x, win_y) — x slow,
                    ax, rows_c, (((2,), (2,)), ((0,), (0,))),  # ops.corr
                    preferred_element_type=jnp.float32)  # channel order
                win_ref[...] += wp.reshape(p_block, win * win)
            return 0

        jax.lax.fori_loop(0, n_blocks, body, 0)

        if fused:
            w_lvl = w_ref[pl.ds(lvl * win * win, win * win), :]
            acc_ref[...] += jnp.dot(win_ref[...], w_lvl.astype(jnp.float32),
                                    preferred_element_type=jnp.float32)
        else:
            out_ref[0, :, lvl * win * win:(lvl + 1) * win * win] = win_ref[...]
    if fused:
        out_ref[0] = acc_ref[...]


def _flash_forward(fmap1: jax.Array, fmap2_levels: tuple, coords: jax.Array,
                   weight, bias, radius: int, interpret=None) -> jax.Array:
    """Shared XLA-side prep for the fused (weight/bias given) and lookup
    (weight=bias=None) flash kernels. fmap2 levels are padded only to a
    row-block multiple (zero rows read as out-of-frame) and enter the
    kernel in HBM; everything else is pixel-blocked into VMEM."""
    if interpret is None:
        interpret = _interpret_default()
    b, h, w, c = fmap1.shape
    r = radius
    win = 2 * r + 1
    num_levels = len(fmap2_levels)
    fused = weight is not None
    rows = _flash_rows()
    pixel_block = _flash_pixel_block()

    # degenerate 0-row/0-col tail levels (a 1x1 level pools to nothing)
    # never enter the kernel: their windows are identically zero, and a
    # zero-size operand cannot flow through pallas_call
    level_ids = tuple(i for i, f2 in enumerate(fmap2_levels)
                      if f2.shape[1] > 0 and f2.shape[2] > 0)
    if not level_ids:
        # every staged level is degenerate (single-level call on a
        # pooled-away tail): the window features are identically zero,
        # so the fused output is just the broadcast bias
        if fused:
            return jnp.broadcast_to(bias.astype(jnp.float32),
                                    (b, h, w, weight.shape[1]))
        return jnp.zeros((b, h, w, num_levels * win * win), jnp.float32)
    kept = [fmap2_levels[i] for i in level_ids]
    level_shapes = tuple(f2.shape[1:3] for f2 in kept)

    # pad each level's rows to the DMA block size in the STORAGE dtype
    # (fp32/bf16/int8 — the quantized bytes are what stream HBM->VMEM)
    f2p = [jnp.pad(f2, ((0, 0), (0, (-f2.shape[1]) % rows),
                        (0, 0), (0, 0)))
           for f2 in kept]
    w2_max = max(s[1] for s in level_shapes)

    n = h * w
    n_pad = (-n) % pixel_block
    np_tot = n + n_pad
    flat = lambda a: jnp.pad(  # noqa: E731
        a.reshape(b, n, a.shape[3]), ((0, 0), (0, n_pad), (0, 0)))
    f1_flat = flat(fmap1.astype(jnp.float32))
    # padded tail queries carry coords 0 — they force row block 0 of each
    # level to be fetched, compute a real window, and are sliced away
    co_flat = flat(coords.astype(jnp.float32))

    grid = (b, np_tot // pixel_block)
    f1_spec = pl.BlockSpec((1, pixel_block, c), lambda bi, ti: (bi, ti, 0),
                           memory_space=pltpu.VMEM)
    co_spec = pl.BlockSpec((1, pixel_block, 2), lambda bi, ti: (bi, ti, 0),
                           memory_space=pltpu.VMEM)
    inputs = [f1_flat, co_flat]
    in_specs = [f1_spec, co_spec]
    if fused:
        feat = weight.shape[1]
        inputs += [weight.astype(jnp.float32),
                   bias.reshape(1, feat).astype(jnp.float32)]
        in_specs += [
            pl.BlockSpec((num_levels * win * win, feat),
                         lambda bi, ti: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, feat), lambda bi, ti: (0, 0),
                         memory_space=pltpu.VMEM),
        ]
        out_ch = feat
    else:
        out_ch = num_levels * win * win
    # the fmap2 levels: full arrays, HBM-resident — the kernel DMAs row
    # blocks on demand
    inputs += f2p
    in_specs += [pl.BlockSpec(memory_space=pltpu.ANY)] * len(f2p)

    scratch = [pltpu.VMEM((rows, w2_max, c), f2p[0].dtype),
               pltpu.VMEM((pixel_block, win * win), jnp.float32)]
    if fused:
        scratch.append(pltpu.VMEM((pixel_block, out_ch), jnp.float32))
    scratch.append(pltpu.SemaphoreType.DMA)

    kernel = functools.partial(_flash_kernel, radius=r,
                               level_ids=level_ids,
                               level_shapes=level_shapes,
                               num_levels_total=num_levels,
                               rows=rows, fused=fused)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, pixel_block, out_ch),
                               lambda bi, ti: (bi, ti, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, np_tot, out_ch), jnp.float32),
        scratch_shapes=scratch,
        interpret=interpret,
    )(*inputs)
    return out[:, :n].reshape(b, h, w, out_ch)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_local_corr_level(fmap1, fmap2, coords, radius: int,
                           interpret=None, row_chunk=8):
    """Flash-blocked single-level lookup: same signature/semantics as
    pallas_local_corr_level (coords in LEVEL pixels, zero coords grad,
    VJP recomputes through local_corr_level) but fmap2 stays in HBM and
    the window is built from blocked MXU matmuls, not per-pixel slices."""
    return _flash_forward(fmap1, (fmap2,), coords, None, None, radius,
                          interpret)


def _flash_level_fwd(fmap1, fmap2, coords, radius, interpret, row_chunk):
    return (_flash_forward(fmap1, (fmap2,), coords, None, None, radius,
                           interpret),
            (fmap1, fmap2, coords))


flash_local_corr_level.defvjp(_flash_level_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def flash_fused_step(fmap1, fmap2_levels, coords, weight, bias,
                     radius: int, interpret=None, row_chunk=8):
    """Flash-blocked fused lookup+update-entry — pallas_fused_step's
    signature and custom-VJP contract (recompute through fused_reference,
    zero coords grad, int8 levels -> float0), ONE kernel per refinement
    iteration at ANY geometry: only the fmaps live in HBM, the window
    features and per-level intermediates never leave VMEM, and there is
    no VMEM-budget split path (levels are row-block-streamed, not staged
    whole)."""
    return _flash_forward(fmap1, tuple(fmap2_levels), coords, weight, bias,
                          radius, interpret)


def _flash_fused_fwd(fmap1, fmap2_levels, coords, weight, bias, radius,
                     interpret, row_chunk):
    out = _flash_forward(fmap1, tuple(fmap2_levels), coords, weight, bias,
                         radius, interpret)
    return out, (fmap1, tuple(fmap2_levels), coords, weight, bias)


flash_fused_step.defvjp(_flash_fused_fwd, _fused_bwd)
