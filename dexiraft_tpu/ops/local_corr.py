"""Memory-efficient local correlation — the alt_cuda_corr equivalent.

The reference's CUDA kernel (alt_cuda_corr/correlation_kernel.cu:19-119)
computes, per query pixel, dot products of fmap1 against an integer
lattice of fmap2 rows around floor(coords) and scatter-accumulates the 4
bilinear corner weights into a (2r+1)^2 window. O(HW * (2r+2)^2) memory
instead of the materialized volume's O((HW)^2) (SURVEY.md §2.2).

TPU-native reformulation — flash-attention-style, all MXU matmuls:
per chunk of query rows, the partial all-pairs block
vol = f1_chunk · f2ᵀ (ops.corr.all_pairs_correlation) is materialized,
windowed with the separable one-hot interpolation matmuls of
ops.corr.interp_window, and discarded. Transient memory is
O(chunk · W · H2 · W2) per level (`row_chunk` bounds it; lax.map keeps
chunks sequential), never the full volume, and there are zero gather
HLOs — TPU gathers measured 16-30x slower than recomputing the dots on
the MXU.

Like the reference's AlternateCorrBlock (core/corr.py:63-91), the pyramid
pools FMAP2 (not the correlation volume) — since build_corr_pyramid now
exploits the same linearity, the two paths agree to reassociation noise.
Out-of-frame lattice points contribute zero, matching bilinear_sampler's
zero padding.

Gradients flow to fmap1/fmap2 through the matmuls; coords get zero
gradient (stop_gradient), replicating the CUDA backward's never-written
coords_grad (correlation_kernel.cu:307). The reference's Python wrapper
has NO autograd at all (core/corr.py:86 calls the op directly) — ours is
trainable, a strict capability superset.
"""

from __future__ import annotations

from typing import List, Optional

import flax.struct
import jax
import jax.numpy as jnp

from dexiraft_tpu.ops.corr import avg_pool_2x2
from dexiraft_tpu.ops.quant import store_corr


def local_corr_level(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    row_chunk: Optional[int] = None,
) -> jax.Array:
    """Windowed correlation of fmap1 against fmap2 around coords.

    fmap1: (B, H, W, C) query features (level-0 resolution)
    fmap2: (B, H2, W2, C) target features at this pyramid level
    coords: (B, H, W, 2) sample centers in LEVEL pixels (x, y)
    Returns (B, H, W, (2r+1)^2) float32.

    Flash-attention-style formulation: per query-row chunk, the partial
    all-pairs block vol = f1_chunk · f2ᵀ (MXU matmul) is materialized,
    windowed via the separable one-hot interpolation matmuls of
    ops.corr.corr_lookup, and discarded — O(chunk·H2·W2) transient memory,
    never the full O((HW)²) volume, and zero gather HLOs (TPU gathers
    measured ~16-30x slower than rebuilding the dots on the MXU).
    """
    b, h, w, c = fmap1.shape
    coords = jax.lax.stop_gradient(coords)

    if row_chunk is not None and row_chunk < h:
        pad = (-h) % row_chunk
        f1 = jnp.pad(fmap1, ((0, 0), (0, pad), (0, 0), (0, 0)))
        co = jnp.pad(coords, ((0, 0), (0, pad), (0, 0), (0, 0)))
        n_chunks = (h + pad) // row_chunk
        f1 = f1.reshape(b, n_chunks, row_chunk, w, c).swapaxes(0, 1)
        co = co.reshape(b, n_chunks, row_chunk, w, 2).swapaxes(0, 1)
        out = jax.lax.map(
            lambda args: _local_corr_dense(args[0], fmap2, args[1], radius),
            (f1, co),
        )  # (n_chunks, B, row_chunk, W, win^2)
        out = out.swapaxes(0, 1).reshape(b, h + pad, w, -1)
        return out[:, :h]
    return _local_corr_dense(fmap1, fmap2, coords, radius)


def _local_corr_dense(
    fmap1: jax.Array, fmap2: jax.Array, coords: jax.Array, radius: int
) -> jax.Array:
    from dexiraft_tpu.ops.corr import all_pairs_correlation, interp_window

    b, h, w, _ = fmap1.shape
    win = 2 * radius + 1
    # partial all-pairs block for these queries (fp32 accumulate, MXU)
    vol = all_pairs_correlation(fmap1, fmap2)  # (B*H*W, H2, W2, 1)
    flat = coords.reshape(b * h * w, 2).astype(jnp.float32)
    window = interp_window(vol[..., 0], flat, radius)
    return window.reshape(b, h, w, win * win)


@flax.struct.dataclass
class LocalCorr:
    """On-demand correlation pyramid: same lookup interface as CorrPyramid.

    Holds fmap1 and the avg-pooled fmap2 pyramid (core/corr.py:64-72);
    correlation is computed per lookup instead of materialized.
    """

    fmap1: jax.Array  # (B, H, W, C), fp32
    fmap2_pyramid: tuple  # tuple of (B, H>>i, W>>i, C) in the storage dtype
    batch: int = flax.struct.field(pytree_node=False)
    ht: int = flax.struct.field(pytree_node=False)
    wd: int = flax.struct.field(pytree_node=False)
    radius: int = flax.struct.field(pytree_node=False)
    row_chunk: Optional[int] = flax.struct.field(pytree_node=False, default=None)
    # lookup implementation: "xla" (local_corr_level matmuls), "pallas"
    # (per-pixel slice kernel), "flash" (blocked HBM-streaming kernel —
    # ops/pallas_corr.py flash_local_corr_level / flash_fused_step)
    kernel: str = flax.struct.field(pytree_node=False, default="xla")
    # per-level fp32 scalar dequantization scales for int8-stored fmap2
    # levels (ops/quant.py); None for fp32/bf16. Correlation is linear in
    # fmap2, so corr(f1, s*q) = s * corr(f1, q): the scale multiplies the
    # looked-up window AFTER the kernel — the quantized level is what
    # streams from HBM, and no dequantized copy is ever materialized.
    scales: Optional[tuple] = None

    def level_scale(self, i: int) -> Optional[jax.Array]:
        return self.scales[i] if self.scales is not None else None

    def __call__(self, coords: jax.Array) -> jax.Array:
        """coords (B, H, W, 2) in level-0 pixels -> (B, H, W, L*(2r+1)^2)."""
        out: List[jax.Array] = []
        for i, f2 in enumerate(self.fmap2_pyramid):
            coords_i = coords / (2.0 ** i)
            if self.kernel in ("pallas", "flash"):
                from dexiraft_tpu.ops.pallas_corr import (
                    flash_local_corr_level,
                    pallas_local_corr_level,
                )

                # interpret=None defers to the kernel module's
                # DEXIRAFT_PALLAS_INTERPRET env knob, which makes these
                # whole-model paths exercisable off-chip
                # (tests/test_local_corr.py, tests/test_zzzflashcorr.py)
                level = (flash_local_corr_level if self.kernel == "flash"
                         else pallas_local_corr_level)
                corr = level(self.fmap1, f2, coords_i, self.radius,
                             None, self.row_chunk)
            else:
                corr = local_corr_level(
                    self.fmap1, f2, coords_i, self.radius, self.row_chunk)
            scale = self.level_scale(i)
            if scale is not None:
                corr = corr * scale
            out.append(corr)
        return jnp.concatenate(out, axis=-1).astype(jnp.float32)


def build_local_corr(
    fmap1: jax.Array,
    fmap2: jax.Array,
    num_levels: int = 4,
    radius: int = 4,
    row_chunk: Optional[int] = None,
    use_pallas: bool = False,
    dtype: str = "fp32",
    kernel: Optional[str] = None,
) -> LocalCorr:
    """Build the pooled-fmap2 pyramid (no volume materialization).

    ``dtype`` sets the STORAGE precision of the fmap2 pyramid (the tensor
    every on-demand lookup streams; fmap1 stays fp32 — it is read once
    per pixel block, not once per lattice point). Pooling runs fp32; each
    level is then stored bf16/int8 with a per-level scale (ops/quant.py)
    and the lookup dequantizes in-register.

    ``kernel`` picks the lookup implementation ("xla" | "pallas" |
    "flash"); ``use_pallas`` is the legacy boolean spelling of
    kernel="pallas" and is ignored when ``kernel`` is given.
    """
    if kernel is None:
        kernel = "pallas" if use_pallas else "xla"
    if kernel not in ("xla", "pallas", "flash"):
        raise ValueError(f"unknown local-corr kernel {kernel!r}; "
                         "expected 'xla', 'pallas', or 'flash'")
    b, h, w, _ = fmap1.shape
    f1 = fmap1.astype(jnp.float32)
    pooled = [fmap2.astype(jnp.float32)]
    for _ in range(num_levels - 1):
        pooled.append(avg_pool_2x2(pooled[-1]))
    stored = [store_corr(lvl, dtype) for lvl in pooled]
    return LocalCorr(
        fmap1=f1, fmap2_pyramid=tuple(s[0] for s in stored),
        batch=b, ht=h, wd=w,
        radius=radius, row_chunk=row_chunk, kernel=kernel,
        scales=(tuple(s[1] for s in stored) if dtype == "int8" else None))
