"""Memory-efficient local correlation — the alt_cuda_corr equivalent.

The reference's CUDA kernel (alt_cuda_corr/correlation_kernel.cu:19-119)
computes, per query pixel, dot products of fmap1 against an integer
lattice of fmap2 rows around floor(coords) and scatter-accumulates the 4
bilinear corner weights into a (2r+1)^2 window. O(HW * (2r+2)^2) memory
instead of the materialized volume's O((HW)^2) (SURVEY.md §2.2).

TPU-native reformulation (gather, not scatter):
  1. gather the (2r+2)^2 integer patch of fmap2 around floor(coords)
     (XLA gather HLO — the embedding-lookup path, HBM-bandwidth bound);
  2. one batched einsum against fmap1 for the integer-lattice dots;
  3. blend the 4 corners on the VPU: window[j] = sum_c w_c * lattice[j + c]
     — the exact transpose of the CUDA kernel's scatter.

Like the reference's AlternateCorrBlock (core/corr.py:63-91), the pyramid
pools FMAP2 (not the correlation volume), so numerics differ slightly
from the materialized path at levels > 0 — the same approximation the
reference makes. Out-of-frame lattice points contribute zero, matching
bilinear_sampler's zero padding.

Gradients flow to fmap1/fmap2 through the gather/einsum; coords get zero
gradient (stop_gradient), replicating the CUDA backward's never-written
coords_grad (correlation_kernel.cu:307). The reference's Python wrapper
has NO autograd at all (core/corr.py:86 calls the op directly) — ours is
trainable, a strict capability superset.

Row-chunking (lax.map over row blocks) bounds the transient patch buffer:
full-frame Sintel eval would otherwise materialize
HW * (2r+2)^2 * C * 4B ≈ 720 MB per level.
"""

from __future__ import annotations

from typing import List, Optional

import flax.struct
import jax
import jax.numpy as jnp

from dexiraft_tpu.ops.corr import avg_pool_2x2


def local_corr_level(
    fmap1: jax.Array,
    fmap2: jax.Array,
    coords: jax.Array,
    radius: int,
    row_chunk: Optional[int] = None,
) -> jax.Array:
    """Windowed correlation of fmap1 against fmap2 around coords.

    fmap1: (B, H, W, C) query features (level-0 resolution)
    fmap2: (B, H2, W2, C) target features at this pyramid level
    coords: (B, H, W, 2) sample centers in LEVEL pixels (x, y)
    Returns (B, H, W, (2r+1)^2) float32.
    """
    b, h, w, c = fmap1.shape
    coords = jax.lax.stop_gradient(coords)

    if row_chunk is not None and row_chunk < h:
        pad = (-h) % row_chunk
        f1 = jnp.pad(fmap1, ((0, 0), (0, pad), (0, 0), (0, 0)))
        co = jnp.pad(coords, ((0, 0), (0, pad), (0, 0), (0, 0)))
        n_chunks = (h + pad) // row_chunk
        f1 = f1.reshape(b, n_chunks, row_chunk, w, c).swapaxes(0, 1)
        co = co.reshape(b, n_chunks, row_chunk, w, 2).swapaxes(0, 1)
        out = jax.lax.map(
            lambda args: _local_corr_dense(args[0], fmap2, args[1], radius),
            (f1, co),
        )  # (n_chunks, B, row_chunk, W, win^2)
        out = out.swapaxes(0, 1).reshape(b, h + pad, w, -1)
        return out[:, :h]
    return _local_corr_dense(fmap1, fmap2, coords, radius)


def _local_corr_dense(
    fmap1: jax.Array, fmap2: jax.Array, coords: jax.Array, radius: int
) -> jax.Array:
    b, h, w, c = fmap1.shape
    h2, w2 = fmap2.shape[1:3]
    r = radius
    k = 2 * r + 2  # integer lattice extent (window + 1 for bilinear)

    x = coords[..., 0].astype(jnp.float32)
    y = coords[..., 1].astype(jnp.float32)
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    fx = (x - x0)[..., None, None]  # (B, H, W, 1, 1)
    fy = (y - y0)[..., None, None]

    offs = jnp.arange(-r, r + 2, dtype=jnp.int32)  # (k,)
    xs = x0.astype(jnp.int32)[..., None] + offs  # (B, H, W, k)
    ys = y0.astype(jnp.int32)[..., None] + offs

    vx = (xs >= 0) & (xs < w2)
    vy = (ys >= 0) & (ys < h2)
    xs_c = jnp.clip(xs, 0, w2 - 1)
    ys_c = jnp.clip(ys, 0, h2 - 1)

    # (B, H, W, k, k) flat indices into fmap2's H2*W2 axis: [ky, kx]
    lin = ys_c[..., :, None] * w2 + xs_c[..., None, :]
    valid = (vy[..., :, None] & vx[..., None, :]).astype(jnp.float32)

    f2 = fmap2.reshape(b, h2 * w2, c)
    patches = jnp.take_along_axis(
        f2[:, None, :, :],
        lin.reshape(b, 1, h * w * k * k, 1),
        axis=2,
    ).reshape(b, h, w, k, k, c)

    # integer-lattice dot products, fp32 accumulate (MXU)
    lattice = jnp.einsum(
        "bhwc,bhwijc->bhwij",
        fmap1.astype(jnp.float32),
        patches.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    lattice = lattice * valid / jnp.sqrt(jnp.float32(c))

    # bilinear corner blend: out[j] = sum_{cy,cx} w * lattice[j+cy, j+cx]
    win = 2 * r + 1
    tl = lattice[..., 0:win, 0:win]
    tr = lattice[..., 0:win, 1:win + 1]
    bl = lattice[..., 1:win + 1, 0:win]
    br = lattice[..., 1:win + 1, 1:win + 1]
    out = ((1 - fy) * (1 - fx) * tl + (1 - fy) * fx * tr
           + fy * (1 - fx) * bl + fy * fx * br)
    # lattice axes are (y-offset, x-offset); the reference channel order
    # has the x offset on the SLOW axis (transposed window,
    # core/corr.py:37-43 — see ops.corr._window_delta), so swap before
    # flattening to stay bit-compatible with the allpairs path
    return out.swapaxes(-2, -1).reshape(b, h, w, win * win)


@flax.struct.dataclass
class LocalCorr:
    """On-demand correlation pyramid: same lookup interface as CorrPyramid.

    Holds fmap1 and the avg-pooled fmap2 pyramid (core/corr.py:64-72);
    correlation is computed per lookup instead of materialized.
    """

    fmap1: jax.Array  # (B, H, W, C)
    fmap2_pyramid: tuple  # tuple of (B, H>>i, W>>i, C)
    batch: int = flax.struct.field(pytree_node=False)
    ht: int = flax.struct.field(pytree_node=False)
    wd: int = flax.struct.field(pytree_node=False)
    radius: int = flax.struct.field(pytree_node=False)
    row_chunk: Optional[int] = flax.struct.field(pytree_node=False, default=None)
    use_pallas: bool = flax.struct.field(pytree_node=False, default=False)

    def __call__(self, coords: jax.Array) -> jax.Array:
        """coords (B, H, W, 2) in level-0 pixels -> (B, H, W, L*(2r+1)^2)."""
        out: List[jax.Array] = []
        for i, f2 in enumerate(self.fmap2_pyramid):
            coords_i = coords / (2.0 ** i)
            if self.use_pallas:
                from dexiraft_tpu.ops.pallas_corr import pallas_local_corr_level
                corr = pallas_local_corr_level(
                    self.fmap1, f2, coords_i, self.radius,
                    False, self.row_chunk)
            else:
                corr = local_corr_level(
                    self.fmap1, f2, coords_i, self.radius, self.row_chunk)
            out.append(corr)
        return jnp.concatenate(out, axis=-1).astype(jnp.float32)


def build_local_corr(
    fmap1: jax.Array,
    fmap2: jax.Array,
    num_levels: int = 4,
    radius: int = 4,
    row_chunk: Optional[int] = None,
    use_pallas: bool = False,
) -> LocalCorr:
    """Build the pooled-fmap2 pyramid (no volume materialization)."""
    b, h, w, _ = fmap1.shape
    f1 = fmap1.astype(jnp.float32)
    levels = [fmap2.astype(jnp.float32)]
    for _ in range(num_levels - 1):
        levels.append(avg_pool_2x2(levels[-1]))
    return LocalCorr(
        fmap1=f1, fmap2_pyramid=tuple(levels), batch=b, ht=h, wd=w,
        radius=radius, row_chunk=row_chunk, use_pallas=use_pallas)
