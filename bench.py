"""Benchmark harness — the north-star metric.

Measures refinement iters/sec/chip for the flagship v5 Dexi-RAFT at the
Sintel eval resolution 436x1024 (padded to 440x1024, InputPadder contract),
test-mode forward with 32 refinement iterations — the configuration of
BASELINE.json ("refinement iters/sec/chip at 436x1024") and of
validate_sintel in the reference (evaluate.py:102-133, iters=32).

The reference records NO throughput numbers (BASELINE.md); vs_baseline is
computed against an estimated 320 refinement iters/sec for the reference's
CUDA path on a single modern GPU (upstream RAFT reports ~10 FPS at
1024x436 with 32 iters; 10*32=320). That estimate is carried in
BASELINE_ITERS_PER_SEC below so the driver's record is reproducible.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_ITERS_PER_SEC = 320.0
ITERS = 32
HEIGHT, WIDTH = 440, 1024  # 436 padded to /8 (core/utils/utils.py:7-19)


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}", file=sys.stderr)


_T0 = time.perf_counter()


def _tpu_responsive(timeout_s: float = 300.0) -> bool:
    """Probe the TPU in a SUBPROCESS: a wedged relay tunnel hangs inside
    backend init (it does not raise), and an in-process hung init would
    deadlock any later backend switch.

    The child carries its own watchdog thread that os._exit(3)s on
    timeout — exiting itself rather than being SIGKILLed mid-claim (a
    killed claim holder can wedge a healthy-but-busy tunnel; see
    .claude/skills/verify/SKILL.md). The timeout is generous so only a
    truly wedged tunnel trips it, and the parent timeout is just a
    backstop."""
    import subprocess

    child = (
        "import os, threading, sys\n"
        f"threading.Timer({timeout_s}, lambda: os._exit(3)).start()\n"
        "import jax, jax.numpy as jnp\n"
        "print(float(jax.jit(lambda x: jnp.sum(x))(jnp.ones((2, 2)))))\n"
        "os._exit(0)\n"
    )
    try:
        r = subprocess.run([sys.executable, "-c", child],
                           timeout=timeout_s + 60, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main() -> None:
    import os

    want_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    if not want_cpu and os.environ.get("JAX_PLATFORMS", "") \
            and not _tpu_responsive():
        print("[bench] TPU tunnel unresponsive; CPU fallback", file=sys.stderr)
        want_cpu = True
    import jax

    if want_cpu:
        # the axon site hook re-pins JAX_PLATFORMS; config.update after
        # import is the only reliable override (verify SKILL.md)
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")

    try:
        platform = jax.devices()[0].platform
    except RuntimeError as e:  # backend registration failed outright
        print(f"[bench] TPU backend unavailable ({e}); CPU fallback",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform
    import jax.numpy as jnp

    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.models.raft import RAFT

    _log(f"platform={platform}")

    # jit the init: eagerly it is hundreds of separate dispatches, which
    # through the TPU relay tunnel costs minutes
    rng = jax.random.PRNGKey(0)
    small = jnp.zeros((1, 64, 64, 3), jnp.float32)
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    image1 = jax.random.uniform(k1, (1, HEIGHT, WIDTH, 3), jnp.float32, 0, 255)
    image2 = jax.random.uniform(k2, (1, HEIGHT, WIDTH, 3), jnp.float32, 0, 255)

    # the sync fetch costs one tunnel round-trip (~65-115 ms); measure
    # that floor so it can be subtracted from the chained timings below
    trivial = jax.jit(lambda x: jnp.sum(x))
    float(trivial(jnp.ones((8, 8))))
    t0 = time.perf_counter()
    for _ in range(4):
        float(trivial(jnp.ones((8, 8))))
    rtt = (time.perf_counter() - t0) / 4
    _log(f"rtt floor {rtt * 1e3:.1f} ms")

    def measure(corr_impl: str):
        cfg = raft_v5(mixed_precision=(platform == "tpu"),
                      corr_impl=corr_impl)
        model = RAFT(cfg)
        init = jax.jit(
            lambda r, a, b: model.init(r, a, b, iters=1, train=False))
        variables = jax.block_until_ready(init(rng, small, small))
        _log(f"[{corr_impl}] init done")

        def make_forward(iters):
            @jax.jit
            def forward(a, b):
                low, up = model.apply(variables, a, b, iters=iters,
                                      train=False, test_mode=True)
                # reduce to one scalar: block_until_ready over the relay
                # tunnel does not reliably block, so fetching this value
                # is the only sync point that provably postdates the
                # whole computation
                return jnp.sum(low) + jnp.sum(up)
            return forward

        def timed_raw(fn, reps):
            """Mean wall time of float(fn(...)) — INCLUDES one tunnel
            round-trip per fetch."""
            float(fn(image1, image2))  # compile + warmup
            t0 = time.perf_counter()
            for _ in range(reps):
                float(fn(image1, image2))
            return (time.perf_counter() - t0) / reps

        def rtt_corrected(dt):
            # each fetch pays one tunnel round-trip that is measurement
            # overhead, not compute — subtract the measured floor.
            # (Chaining forwards inside one lax.scan to amortize the RTT
            # instead was tried and rejected: the while-loop wrapper
            # defeated XLA's scheduler and ran the same forward 26x
            # slower.)
            if dt <= rtt:
                # the floor is measured once and RTT varies; never let
                # the correction publish a nonsense (near-zero) timing —
                # fall back to the uncorrected, conservative number
                _log(f"WARNING: timing {dt * 1e3:.1f} ms <= rtt floor "
                     f"{rtt * 1e3:.1f} ms; reporting uncorrected")
                return dt
            return dt - rtt

        reps = 3 if platform == "tpu" else 1
        raw = timed_raw(make_forward(ITERS), reps)
        dt = rtt_corrected(raw)
        _log(f"[{corr_impl}] steady-state {dt * 1e3:.1f} ms / forward")

        loop_rate = None
        if platform == "tpu":
            # marginal per-iteration rate: isolates the refinement loop
            # from the amortized prelude (encoders/DexiNed/volume build)
            # — the number directly comparable to a per-lookup kernel.
            # Computed from the RAW difference: both timings carry the
            # same one-RTT overhead, so it cancels exactly regardless of
            # whether the floor correction applied to either
            raw1 = timed_raw(make_forward(1), reps)
            if raw > raw1:
                loop_rate = (ITERS - 1) / (raw - raw1)
            _log(f"[{corr_impl}] prelude+1 {rtt_corrected(raw1) * 1e3:.1f} ms; "
                 f"loop {loop_rate and round(loop_rate, 1)} iters/s")
        return ITERS / dt, loop_rate

    # both first-class corr paths are measured: the materialized MXU
    # volume and the memory-efficient on-demand path (the alt_cuda_corr
    # analog the north-star metric names, BASELINE.json); the faster one
    # is the headline — a user picks it with one config flag
    allpairs_ips, allpairs_loop = measure("allpairs")
    local_ips = local_loop = None
    if platform == "tpu":  # secondary metric; not worth CPU-fallback time
        try:
            local_ips, local_loop = measure("local")
        except Exception as e:  # never lose the primary number
            _log(f"[local] failed: {e}")

    if local_ips is not None and local_ips > allpairs_ips:
        iters_per_sec, loop_ips, impl = local_ips, local_loop, "local"
    else:
        iters_per_sec, loop_ips, impl = allpairs_ips, allpairs_loop, "allpairs"

    print(json.dumps({
        "metric": f"refinement_iters_per_sec_per_chip@{HEIGHT}x{WIDTH}",
        "value": round(iters_per_sec, 2),
        "unit": "iters/s",
        # conservative: the headline amortizes the whole forward incl.
        # the DexiNed+encoder prelude over the 32 iterations, while the
        # 320 it/s denominator is an upstream-RAFT estimate WITHOUT the
        # dual edge stream or DexiNed the v5 model also runs
        "vs_baseline": round(iters_per_sec / BASELINE_ITERS_PER_SEC, 3),
        "corr_impl": impl,
        "loop_only_iters_per_sec": (round(loop_ips, 2) if loop_ips
                                    else None),
        # the marginal refinement-loop rate vs the same denominator —
        # the directly comparable "refinement iters/sec" number
        "vs_baseline_loop_only": (round(loop_ips / BASELINE_ITERS_PER_SEC, 3)
                                  if loop_ips else None),
        "allpairs_iters_per_sec": round(allpairs_ips, 2),
        "local_corr_iters_per_sec": (round(local_ips, 2)
                                     if local_ips else None),
    }))


if __name__ == "__main__":
    main()
