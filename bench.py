"""Benchmark harness — the north-star metric.

Measures refinement iters/sec/chip for the flagship v5 Dexi-RAFT at the
Sintel eval resolution 436x1024 (padded to 440x1024, InputPadder contract),
test-mode forward with 32 refinement iterations — the configuration of
BASELINE.json ("refinement iters/sec/chip at 436x1024") and of
validate_sintel in the reference (evaluate.py:102-133, iters=32).

The reference records NO throughput numbers (BASELINE.md); vs_baseline is
computed against an estimated 320 refinement iters/sec for the reference's
CUDA path on a single modern GPU (upstream RAFT reports ~10 FPS at
1024x436 with 32 iters; 10*32=320). That estimate is carried in
BASELINE_ITERS_PER_SEC below so the driver's record is reproducible.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

BASELINE_ITERS_PER_SEC = 320.0
ITERS = 32
HEIGHT, WIDTH = 440, 1024  # 436 padded to /8 (core/utils/utils.py:7-19)


def main() -> None:
    from dexiraft_tpu.config import raft_v5
    from dexiraft_tpu.models.raft import RAFT

    platform = jax.devices()[0].platform
    # The materialized all-pairs volume at this resolution is (55*128)^2 fp32
    # per stream; the memory-efficient local path is the bench target once
    # wired (mirrors the reference benching alt_cuda_corr). Until then bench
    # allpairs — it fits v5e HBM at batch 1.
    cfg = raft_v5(mixed_precision=(platform == "tpu"))
    model = RAFT(cfg)

    rng = jax.random.PRNGKey(0)
    small = jnp.zeros((1, 64, 64, 3), jnp.float32)
    variables = model.init(rng, small, small, iters=1, train=False)

    @jax.jit
    def forward(image1, image2):
        return model.apply(variables, image1, image2, iters=ITERS,
                           train=False, test_mode=True)

    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    image1 = jax.random.uniform(k1, (1, HEIGHT, WIDTH, 3), jnp.float32, 0, 255)
    image2 = jax.random.uniform(k2, (1, HEIGHT, WIDTH, 3), jnp.float32, 0, 255)

    # compile + warmup
    jax.block_until_ready(forward(image1, image2))

    reps = 5
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(forward(image1, image2))
    dt = (time.perf_counter() - t0) / reps

    iters_per_sec = ITERS / dt
    print(json.dumps({
        "metric": f"refinement_iters_per_sec_per_chip@{HEIGHT}x{WIDTH}",
        "value": round(iters_per_sec, 2),
        "unit": "iters/s",
        "vs_baseline": round(iters_per_sec / BASELINE_ITERS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
