"""Benchmark harness — the north-star metric.

Measures refinement iters/sec/chip for the flagship v5 Dexi-RAFT at the
Sintel eval resolution 436x1024 (padded to 440x1024, InputPadder contract),
test-mode forward with 32 refinement iterations — the configuration of
BASELINE.json ("refinement iters/sec/chip at 436x1024") and of
validate_sintel in the reference (evaluate.py:102-133, iters=32).

The reference records NO throughput numbers (BASELINE.md); vs_baseline is
computed against an estimated 320 refinement iters/sec for the reference's
CUDA path on a single modern GPU (upstream RAFT reports ~10 FPS at
1024x436 with 32 iters; 10*32=320). That estimate is carried in
BASELINE_ITERS_PER_SEC below and flagged as `baseline_kind: "estimate"`
in the JSON so the record is self-describing.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
The line always carries `platform`; a CPU fallback (tunnel down) is
marked `fallback: true`, runs a deliberately small geometry so it costs
~1 minute instead of ~8, and is never presented as the on-chip headline.
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_ITERS_PER_SEC = 320.0
ITERS = 32

# ---- record schema pin (tests/test_bench_watchdog.py) -------------------
# Top-level keys every bench record MUST carry. The per-config diagnostic
# keys are prefixed (e.g. "allpairs_raw_ms", "fused_pallas_int8_mfu") and
# open-ended; the conditional keys below appear only in the situations
# their comments in main() describe.
BENCH_RECORD_KEYS = frozenset({
    "metric", "value", "unit", "vs_baseline", "platform", "fallback",
    "baseline_kind", "baseline_iters_per_sec", "device_kind", "iters",
    "corr_impl", "corr_impl_resolved", "corr_dtype", "fused_update",
    "dexined_upconv",
    "loop_only_iters_per_sec", "loop_only_vs_whole_forward_baseline",
    "allpairs_iters_per_sec", "local_corr_iters_per_sec",
    "pallas_corr_iters_per_sec", "flash_corr_iters_per_sec",
})
BENCH_RECORD_OPTIONAL_KEYS = frozenset({
    "cpu_anchor_flax_over_torch", "cpu_anchor_flax_over_torch_train",
    "cpu_anchor_source", "builder_tpu_reference", "forward_flops", "mfu",
    "chip_peak_bf16_flops",
})
# every sweep leg's diagnostics land under its tag prefix
BENCH_DIAG_PREFIXES = (
    "allpairs", "local", "pallas", "fused_pallas", "flash",
)


def validate_record(rec: dict) -> None:
    """Schema gate for the ONE JSON line the driver greps: all required
    keys present; nothing outside required + optional + tag-prefixed
    diagnostics. Raises ValueError so a drifted record fails the run
    instead of silently changing shape under the queue tooling."""
    missing = BENCH_RECORD_KEYS - set(rec)
    if missing:
        raise ValueError(f"bench record missing keys: {sorted(missing)}")
    for key in set(rec) - BENCH_RECORD_KEYS - BENCH_RECORD_OPTIONAL_KEYS:
        if not any(key.startswith(p + "_") for p in BENCH_DIAG_PREFIXES):
            raise ValueError(f"bench record carries unpinned key {key!r}; "
                             "extend BENCH_RECORD_KEYS (and the schema "
                             "test) deliberately, not by accident")

# ---- stderr relay hygiene (shared with scripts/serve_bench.py) ----------
# On hosts whose CPU lacks features the wheels were built for, XLA prints
# a warning line carrying this marker. Relayed verbatim by the watchdog
# pumps it lands in the queue's recorded `tail` fields, burying the JSON
# metric line the driver greps for. The filter diverts it: first
# occurrence goes verbatim to a side log and is replaced by a one-line
# note; repeats are dropped.
XLA_HOST_WARNING_MARKER = b"This could lead to execution errors such as SIGILL"


def make_stderr_filter(log_path=None, tag="bench"):
    """Line filter for a watchdog stderr pump: returns fn(line: bytes)
    -> bytes | None. Lines carrying XLA_HOST_WARNING_MARKER are diverted
    — the first is appended verbatim to ``log_path`` (default
    $BENCH_XLA_WARN_LOG or /tmp/xla_host_warning.log) and replaced with
    a short note; later ones return None (drop). Everything else passes
    through untouched, so the relayed stream still ends with the record's
    JSON line."""
    import os

    path = log_path or os.environ.get("BENCH_XLA_WARN_LOG",
                                      "/tmp/xla_host_warning.log")
    seen = [False]

    def filt(line: bytes):
        if XLA_HOST_WARNING_MARKER not in line:
            return line
        if seen[0]:
            return None
        seen[0] = True
        try:
            with open(path, "ab") as fh:
                fh.write(line)
            where = path
        except OSError:
            where = f"unwritable {path}; warning dropped"
        return (f"[{tag}] XLA host-feature warning suppressed "
                f"(full text: {where})\n").encode()

    return filt


HEIGHT, WIDTH = 440, 1024  # 436 padded to /8 (core/utils/utils.py:7-19)
# CPU fallback: the number is diagnostic only (smoke proof the model
# runs), so spend seconds, not minutes, producing it
CPU_ITERS = 6
CPU_HEIGHT, CPU_WIDTH = 224, 512


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:7.1f}s] {msg}", file=sys.stderr)


def _cpu_anchor_fields() -> dict:
    """The measured torch-vs-flax same-CPU anchors, parsed from the
    anchor script's log (one copy of the numbers: the measurement's).
    Per-geometry: the r5 anchor runs pin the framework-vs-framework
    ratio at every benched configuration (VERDICT r4 next-8), so all
    records are carried, keyed by their measured geometry; a re-run of
    the same geometry keeps the freshest value (the log appends)."""
    import os.path as osp

    path = osp.join(osp.dirname(osp.abspath(__file__)),
                    "logs", "torch_cpu_anchor.log")
    fwd: dict = {}
    train: dict = {}
    try:
        with open(path) as f:
            for line in f:
                if not line.lstrip().startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                    metric = rec.get("metric", "")
                    if "@" not in metric:
                        # legacy record without a geometry-bearing
                        # metric name — no key to file it under; skip
                        continue
                    geom = metric.rsplit("@", 1)[-1]
                    if "flax_over_torch" in rec:
                        fwd[geom] = rec["flax_over_torch"]
                    elif "flax_over_torch_train" in rec:
                        train[geom] = rec["flax_over_torch_train"]
                except ValueError:
                    continue
    except OSError:
        pass
    fields: dict = {}
    if fwd:
        fields["cpu_anchor_flax_over_torch"] = fwd
    if train:
        fields["cpu_anchor_flax_over_torch_train"] = train
    if fields:
        fields["cpu_anchor_source"] = "logs/torch_cpu_anchor.log"
    return fields


_T0 = time.perf_counter()

# bf16 peak matmul throughput per chip, by jax device_kind. Used for the
# MFU denominator (VERDICT r4 next-3); the record names the value used so
# the ratio is auditable. Sources: published TPU spec sheets (v5e 197
# bf16 TFLOP/s; v4 275; v3 123; v6e 918). Unknown kinds get no MFU
# rather than a made-up denominator.
CHIP_PEAK_BF16_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,   # v5p
    "TPU v4": 275e12,
    "TPU v4 lite": 138e12,  # v4i
    "TPU v3": 123e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
}


def _counted_flops(jitted, *args):
    """Whole-computation FLOPs from XLA's own cost analysis of the
    compiled executable (not an analytic estimate). Returns None if the
    backend declines — the bench must never fail over accounting."""
    try:
        cost = jitted.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):  # some versions wrap per-device
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        return flops if flops > 0 else None
    except Exception as e:
        _log(f"cost_analysis unavailable: {e}")
        return None


def _tpu_responsive(timeout_s: float = 300.0) -> bool:
    """Probe the TPU in a SUBPROCESS: a wedged relay tunnel hangs inside
    backend init (it does not raise), and an in-process hung init would
    deadlock any later backend switch.

    The child carries its own watchdog thread that os._exit(3)s on
    timeout — exiting itself rather than being SIGKILLed mid-claim (a
    killed claim holder can wedge a healthy-but-busy tunnel; see
    .claude/skills/verify/SKILL.md). The timeout is generous so only a
    truly wedged tunnel trips it, and the parent timeout is just a
    backstop."""
    import subprocess

    child = (
        "import os, threading, sys\n"
        f"threading.Timer({timeout_s}, lambda: os._exit(3)).start()\n"
        "import jax, jax.numpy as jnp\n"
        "if jax.devices()[0].platform == 'cpu': os._exit(4)\n"
        "print(float(jax.jit(lambda x: jnp.sum(x))(jnp.ones((2, 2)))))\n"
        "os._exit(0)\n"
    )
    try:
        r = subprocess.run([sys.executable, "-c", child],
                           timeout=timeout_s + 60, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


# A mid-run relay death leaves device fetches blocked forever (observed
# 2026-07-31: bench hung >15 min after "[allpairs] init done" when the
# tunnel process died under it). The measurement therefore runs in a
# CHILD process; the parent watches for output and, if the child goes
# silent longer than any legitimate compile could take (or overruns the
# hard cap), kills it and re-runs the cheap CPU fallback so the driver
# always gets a JSON line instead of a hang. Env-overridable so the
# watchdog itself is testable (tests/test_bench_watchdog.py).
STALL_S = 900.0
# must leave room for the CPU-fallback child (~5 min incl. interpreter
# start + compile) inside the queue's outer `timeout` on bench_record
# (scripts/tpu_queue.sh) — cap + fallback < queue timeout
HARD_CAP_S = 1950.0


def _run_child(want_cpu: bool) -> tuple[int, bool]:
    """Spawn `bench.py` in measurement mode, forwarding its output.
    Returns (exit code, json_emitted); the child is killed on
    stall/overrun (rc -1). json_emitted reports whether the child got
    its JSON record out before dying — a completed measurement whose
    teardown hung must not be discarded or re-run."""
    import os
    import subprocess
    import threading

    stall_s = float(os.environ.get("BENCH_STALL_S", STALL_S))
    hard_cap_s = float(os.environ.get("BENCH_HARD_CAP_S", HARD_CAP_S))
    env = dict(os.environ, BENCH_CHILD="1")
    if want_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    child = subprocess.Popen([sys.executable, __file__], env=env,
                             stdout=subprocess.PIPE, stderr=subprocess.PIPE)

    # the queue's outer `timeout` SIGTERMs only THIS parent; without a
    # handler the measurement grandchild would be orphaned still holding
    # the TPU claim — forward the kill before dying
    import signal

    def _on_term(signum, frame):
        child.terminate()
        try:
            child.wait(timeout=10)
        except subprocess.TimeoutExpired:
            child.kill()
        sys.exit(128 + signum)

    prev_handlers = {s: signal.signal(s, _on_term)
                     for s in (signal.SIGTERM, signal.SIGINT)}
    last = [time.monotonic()]
    json_seen = [False]
    warn_filt = make_stderr_filter(tag="bench")

    def pump(src, dst, is_stdout):
        for line in iter(src.readline, b""):
            last[0] = time.monotonic()
            if is_stdout and line.lstrip().startswith(b'{"metric"'):
                json_seen[0] = True
            if not is_stdout:
                # keep the XLA host-feature warning out of the relayed
                # stream (and thus the queue's recorded tail)
                line = warn_filt(line)
                if line is None:
                    continue
            dst.buffer.write(line)
            dst.flush()

    threads = [threading.Thread(target=pump, args=(child.stdout, sys.stdout, True), daemon=True),
               threading.Thread(target=pump, args=(child.stderr, sys.stderr, False), daemon=True)]
    for t in threads:
        t.start()
    t0 = time.monotonic()
    rc = None
    while True:
        rc = child.poll()
        if rc is not None:
            break
        time.sleep(min(5.0, stall_s / 2))
        now = time.monotonic()
        if now - last[0] > stall_s or now - t0 > hard_cap_s:
            why = ("silent %.0fs" % (now - last[0])
                   if now - last[0] > stall_s else "overran %.0fs" % hard_cap_s)
            print(f"[bench] child stalled ({why}); killing", file=sys.stderr)
            # SIGTERM first: a SIGKILLed claim holder can wedge a
            # healthy-but-busy tunnel (see _tpu_responsive); give
            # Python/JAX a grace window to release the device claim
            child.terminate()
            try:
                child.wait(timeout=15)
            except subprocess.TimeoutExpired:
                child.kill()
                child.wait()
            rc = -1
            break
    for t in threads:
        t.join(timeout=5)
    for s, h in prev_handlers.items():
        signal.signal(s, h)
    return rc, json_seen[0]


def main() -> None:
    import os

    if not os.environ.get("BENCH_CHILD"):
        want_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
        if not want_cpu and os.environ.get("JAX_PLATFORMS", "") \
                and not _tpu_responsive():
            print("[bench] TPU tunnel unresponsive; CPU fallback",
                  file=sys.stderr)
            want_cpu = True
        rc, json_emitted = _run_child(want_cpu)
        if rc != 0 and json_emitted:
            # the measurement completed and the record is on stdout;
            # only teardown failed (e.g. tunnel died after the last
            # fetch). The record is valid — do NOT emit a second one.
            print(f"[bench] child rc={rc} after emitting its record; "
                  "keeping it", file=sys.stderr)
            rc = 0
        if rc != 0 and not want_cpu:
            # the TPU attempt died or stalled mid-run — produce the
            # diagnostic CPU record rather than nothing
            print("[bench] TPU run failed; CPU fallback", file=sys.stderr)
            rc, json_emitted = _run_child(True)
            if rc != 0 and json_emitted:
                # same rescue as the TPU path: the fallback child got
                # its record out; only teardown failed
                print(f"[bench] fallback child rc={rc} after emitting "
                      "its record; keeping it", file=sys.stderr)
                rc = 0
        sys.exit(rc if rc >= 0 else 8)

    if os.environ.get("BENCH_FAKE_HANG"):
        # test hook (tests/test_bench_watchdog.py): emit one line of
        # progress, then block forever — the parent's stall watchdog
        # must kill us
        print("[bench] fake child hanging", file=sys.stderr, flush=True)
        time.sleep(10_000)

    want_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    import jax

    if want_cpu:
        # the axon site hook re-pins JAX_PLATFORMS; config.update after
        # import is the only reliable override (verify SKILL.md)
        os.environ["JAX_PLATFORMS"] = "cpu"
        jax.config.update("jax_platforms", "cpu")
    # persistent compilation cache: a tunnel that dies mid-bench wastes
    # the compiles already paid — persist them so the next attempt (or
    # the driver's round-end run) resumes warm. Soft no-op if the
    # backend declines to serialize.
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR",
                           os.path.join(os.path.dirname(
                               os.path.abspath(__file__)), ".jax_cache")))
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(os.environ.get(
                "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", 2.0)))
    except Exception as e:
        _log(f"compilation cache unavailable: {e}")

    try:
        platform = jax.devices()[0].platform
    except RuntimeError as e:  # backend registration failed outright
        print(f"[bench] TPU backend unavailable ({e}); CPU fallback",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        platform = jax.devices()[0].platform
    import jax.numpy as jnp

    from dexiraft_tpu.config import raft_v5, resolve_corr_impl
    from dexiraft_tpu.models.raft import RAFT

    on_tpu = platform == "tpu"
    iters = ITERS if on_tpu else CPU_ITERS
    height, width = (HEIGHT, WIDTH) if on_tpu else (CPU_HEIGHT, CPU_WIDTH)
    _log(f"platform={platform} geometry={height}x{width} iters={iters}")

    # jit the init: eagerly it is hundreds of separate dispatches, which
    # through the TPU relay tunnel costs minutes
    rng = jax.random.PRNGKey(0)
    small = jnp.zeros((1, 64, 64, 3), jnp.float32)
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    image1 = jax.random.uniform(k1, (1, height, width, 3), jnp.float32, 0, 255)
    image2 = jax.random.uniform(k2, (1, height, width, 3), jnp.float32, 0, 255)

    trivial = jax.jit(lambda x: jnp.sum(x))
    # ONE device-resident probe operand: creating it inside a strict
    # window would be an implicit host->device constant transfer
    probe = jax.device_put(jnp.ones((8, 8)))
    float(jax.device_get(trivial(probe)))  # compile once, outside timing

    from dexiraft_tpu.analysis import guards

    def measure_rtt(reps: int = 4) -> float:
        # each sync fetch costs one tunnel round-trip (~65-140 ms and it
        # DRIFTS over a session) — measure the floor adjacent to every
        # timed block, not once at startup, so the correction tracks the
        # tunnel's current latency
        t0 = time.perf_counter()
        for _ in range(reps):
            float(jax.device_get(trivial(probe)))
        return (time.perf_counter() - t0) / reps

    def measure(corr_impl: str, upconv: str = "subpixel",
                measure_loop: bool = True, corr_dtype: str = "fp32",
                fused: bool = False):
        cfg = raft_v5(mixed_precision=on_tpu, corr_impl=corr_impl,
                      dexined_upconv=upconv, corr_dtype=corr_dtype,
                      fused_update=fused)
        model = RAFT(cfg)
        init = jax.jit(
            lambda r, a, b: model.init(r, a, b, iters=1, train=False))
        variables = jax.block_until_ready(init(rng, small, small))
        _log(f"[{corr_impl}/{upconv}] init done")

        def make_forward(n):
            @jax.jit
            def forward(a, b):
                low, up = model.apply(variables, a, b, iters=n,
                                      train=False, test_mode=True)
                # reduce to one scalar: block_until_ready over the relay
                # tunnel does not reliably block, so fetching this value
                # is the only sync point that provably postdates the
                # whole computation
                return jnp.sum(low) + jnp.sum(up)
            return forward

        def timed_block(fn, reps):
            """Mean wall time of the synced forward plus the RTT floor
            measured IMMEDIATELY before and after the block (the tunnel
            latency drifts; a stale floor can shift the corrected number
            by 10-25%). Returns (raw_s, rtt_s).

            The timed region runs under guards.strict_mode (the PR 5
            steady-state contract, same as train_bench/serve_bench): the
            warmup call above it absorbs the one expected compile, so
            any retrace or implicit host<->device transfer inside the
            window FAILS the bench instead of deflating the number. The
            sync is an explicit device_get — the sanctioned spelling."""
            float(jax.device_get(fn(image1, image2)))  # compile + warmup
            with guards.strict_mode(label="bench:steady"):
                rtt_pre = measure_rtt()
                t0 = time.perf_counter()
                for _ in range(reps):
                    float(jax.device_get(fn(image1, image2)))
                raw = (time.perf_counter() - t0) / reps
                rtt_post = measure_rtt()
            return raw, (rtt_pre + rtt_post) / 2

        def rtt_corrected(dt, rtt):
            # each fetch pays one tunnel round-trip that is measurement
            # overhead, not compute — subtract the adjacent floor.
            # (Chaining forwards inside one lax.scan to amortize the RTT
            # instead was tried and rejected: the while-loop wrapper
            # defeated XLA's scheduler and ran the same forward 26x
            # slower.)
            if dt <= rtt:
                # never let the correction publish a nonsense
                # (near-zero) timing — fall back to the uncorrected,
                # conservative number
                _log(f"WARNING: timing {dt * 1e3:.1f} ms <= rtt floor "
                     f"{rtt * 1e3:.1f} ms; reporting uncorrected")
                return dt
            return dt - rtt

        def pipeline_time(fn, k):
            # dispatch k forwards back-to-back and fetch ONLY the last
            # result: with async dispatch the wall time is
            # k*compute + 1 RTT, so differencing two k values cancels
            # the RTT (and its drift) exactly. TPU executes one stream
            # in order, so the last result postdates all k computations.
            out = None
            t0 = time.perf_counter()
            for _ in range(k):
                out = fn(image1, image2)
            float(jax.device_get(out))
            return time.perf_counter() - t0

        def slope_time(fn, k=7, rounds=2):
            """Per-forward seconds via the dispatch-pipeline slope
            (T(k) - T(1)) / (k - 1). RTT-free when the relay pipelines
            dispatches; degrades to compute+RTT (today's raw) when it
            serializes them — it can never OVER-subtract, unlike the
            rtt-probe correction, whose floor sometimes drifts 50 ms
            between adjacent probes. min over rounds: wall-clock noise
            is one-sided additive."""
            best = None
            # fn is warm by the time the slope runs (timed_block
            # precedes it), so the slope window is compile-flat too
            with guards.strict_mode(label="bench:slope"):
                for _ in range(rounds):
                    t1 = pipeline_time(fn, 1)
                    tk = pipeline_time(fn, k)
                    s = (tk - t1) / (k - 1)
                    if s > 0 and (best is None or s < best):
                        best = s
            return best

        reps = 3 if on_tpu else 1
        fwd = make_forward(iters)
        raw, rtt = timed_block(fwd, reps)
        dt = rtt_corrected(raw, rtt)
        estimator = "fetch-minus-rtt"
        pipe = slope_time(fwd) if on_tpu else None
        if pipe is not None and pipe < 0.9 * raw:
            # slope clearly below the single-fetch wall time => the
            # relay pipelines dispatches, so the slope is the RTT-free
            # per-forward time — prefer it over the noisy probe
            # subtraction (r5 drift evidence: adjacent floors 61.7 vs
            # 111.7 ms within one minute)
            dt, estimator = pipe, "pipelined-slope"
        _log(f"[{corr_impl}/{upconv}] steady-state {dt * 1e3:.1f} ms / forward "
             f"(raw {raw * 1e3:.1f}, rtt {rtt * 1e3:.1f}, "
             f"slope {pipe and round(pipe * 1e3, 1)}, {estimator})")

        diag = {"raw_ms": round(raw * 1e3, 2), "rtt_ms": round(rtt * 1e3, 2),
                "estimator": estimator}
        if pipe is not None:
            diag["pipelined_slope_ms"] = round(pipe * 1e3, 2)
        # whole-forward FLOPs for the MFU field. The AOT
        # lower().compile() does NOT reuse the in-memory jit executable;
        # it hits the persistent disk cache (enabled unconditionally in
        # this child, above) so it costs seconds of deserialization.
        # Budget-guarded anyway: a cold cache must never push the child
        # into the watchdog's hard cap with the record unprinted.
        if time.perf_counter() - _T0 < float(
                os.environ.get("BENCH_HARD_CAP_S", HARD_CAP_S)) - 650:
            flops = _counted_flops(fwd, image1, image2)
            if flops is not None:
                diag["forward_flops"] = flops
                diag["forward_tflops_per_s"] = round(flops / dt / 1e12, 2)
        else:
            _log(f"[{corr_impl}/{upconv}] flops count skipped (budget)")
        loop_rate = None
        if on_tpu and measure_loop:
            # marginal per-iteration rate: isolates the refinement loop
            # from the amortized prelude (encoders/DexiNed/volume build)
            # — the number directly comparable to a per-lookup kernel.
            # Each raw timing carries one RTT of fetch overhead and the
            # RTT drifts between blocks, so correct each with its OWN
            # adjacent floor before differencing
            fwd1 = make_forward(1)
            raw1, rtt1 = timed_block(fwd1, reps)
            dt1 = rtt_corrected(raw1, rtt1)
            pipe1 = slope_time(fwd1) if estimator == "pipelined-slope" \
                else None
            if pipe1 is not None and pipe1 < 0.9 * raw1:
                # both endpoints from the slope estimator: the marginal
                # rate then contains no RTT term at all
                dt1 = pipe1
                diag["pipelined_slope_1iter_ms"] = round(pipe1 * 1e3, 2)
            signal = dt - dt1
            if signal > 0:
                loop_rate = (iters - 1) / signal
            diag["raw_1iter_ms"] = round(raw1 * 1e3, 2)
            diag["rtt_1iter_ms"] = round(rtt1 * 1e3, 2)
            _log(f"[{corr_impl}/{upconv}] prelude+1 "
                 f"{dt1 * 1e3:.1f} ms; "
                 f"loop {loop_rate and round(loop_rate, 1)} iters/s")
        return iters / dt, loop_rate, diag

    # all three first-class corr paths are measured: the materialized
    # MXU volume, the memory-efficient on-demand path (the alt_cuda_corr
    # analog the north-star metric names, BASELINE.json), and the Pallas
    # VMEM kernel (implemented and parity-tested; in the official sweep
    # per VERDICT r4 §2.2); the fastest is the headline — a user picks
    # it with one config flag. The DexiNed upconv A/B (transposed conv
    # vs the identical-map subpixel phase form) is kept on both
    # non-Pallas corr paths as a diagnostic. The r4 on-chip sweep
    # (logs/tpu_queue_r4/bench_record.log) settled the ordering —
    # allpairs/subpixel won by 1.24x over the runner-up — so the sweep
    # runs BEST-KNOWN-FIRST: if the relay dies mid-sweep, the record
    # that survives is the headline config, not an A/B leg. The upconv
    # choice only changes the prelude, so the transpose variants skip
    # the marginal-loop (1-iter) re-measurement and inherit the loop
    # rate of their subpixel sibling on the same corr path.
    allpairs_ips, allpairs_loop, ap_diag = measure("allpairs", "subpixel")
    diag = {f"allpairs_{k}": v for k, v in ap_diag.items()}
    # candidate = (corr_impl, upconv, corr_dtype, fused, ips, loop_ips)
    candidates = [("allpairs", "subpixel", "fp32", False,
                   allpairs_ips, allpairs_loop)]
    loop_by_corr = {"allpairs": allpairs_loop}
    # the parent kills us at HARD_CAP_S with the record unprinted — if
    # the sweep is running long (slow relay compiles), drop remaining
    # secondary configs and get the JSON out with what we have
    hard_cap_s = float(os.environ.get("BENCH_HARD_CAP_S", HARD_CAP_S))
    secondary_budget_s = float(os.environ.get("BENCH_SECONDARY_BUDGET_S",
                                              hard_cap_s - 550))
    if on_tpu:  # secondary metrics; not worth CPU-fallback time.
        # pallas is on-tpu-only by the same guard: on CPU the kernel
        # runs in interpreter mode — minutes per forward at full
        # geometry, with nothing to learn from the timing. The sweep
        # stays best-known-first; the quantized-pyramid and fused-step
        # legs (this PR's A/B — ISSUE 8) run after the established
        # orderings so a mid-sweep relay death still leaves the
        # headline config measured.
        for corr_impl, upconv, corr_dtype, fused, tag in (
                ("local", "subpixel", "fp32", False, "local"),
                ("pallas", "subpixel", "fp32", False, "pallas"),
                ("allpairs", "subpixel", "bf16", False, "allpairs_bf16"),
                ("allpairs", "subpixel", "int8", False, "allpairs_int8"),
                ("pallas", "subpixel", "fp32", True, "fused_pallas"),
                ("pallas", "subpixel", "int8", True, "fused_pallas_int8"),
                # ISSUE 12's flash-blocked legs: ONE kernel/iteration,
                # fmap2 row-block-streamed from HBM, no materialized
                # volume and no VMEM split path — the candidate for a
                # third allpairs-vs-local ordering flip
                ("flash", "subpixel", "fp32", True, "flash"),
                ("flash", "subpixel", "int8", True, "flash_int8"),
                ("allpairs", "transpose", "fp32", False,
                 "allpairs_transpose"),
                ("local", "transpose", "fp32", False, "local_transpose")):
            if time.perf_counter() - _T0 > secondary_budget_s:
                _log(f"[{tag}] skipped: over secondary budget "
                     f"({secondary_budget_s:.0f}s)")
                continue
            try:
                with_loop = upconv == "subpixel"
                ips, loop, d = measure(corr_impl, upconv,
                                       measure_loop=with_loop,
                                       corr_dtype=corr_dtype, fused=fused)
                diag.update({f"{tag}_{k}": v for k, v in d.items()})
                diag[f"{tag}_iters_per_sec"] = round(ips, 2)
                if loop is not None and corr_dtype == "fp32" and not fused:
                    loop_by_corr[corr_impl] = loop
                candidates.append(
                    (corr_impl, upconv, corr_dtype, fused, ips,
                     loop if loop is not None else loop_by_corr.get(corr_impl)))
            except Exception as e:  # never lose the primary number
                _log(f"[{tag}] failed: {e}")

    impl, upconv_best, dtype_best, fused_best, iters_per_sec, loop_ips = max(
        candidates, key=lambda c: c[4])
    local_ips = diag.get("local_iters_per_sec")

    # MFU of the winning config: counted whole-forward FLOPs (XLA cost
    # analysis of the compiled executable) / measured forward time /
    # chip bf16 peak. Reported only when both the FLOP count and a
    # known chip peak exist; the record names both inputs.
    if fused_best:
        base = "flash" if impl == "flash" else "fused_pallas"
        win_tag = base + ("" if dtype_best == "fp32"
                          else f"_{dtype_best}")
    elif dtype_best != "fp32":
        win_tag = f"{impl}_{dtype_best}"
    else:
        win_tag = impl if upconv_best == "subpixel" else f"{impl}_transpose"
    win_flops = diag.get(f"{win_tag}_forward_flops")
    device_kind = getattr(jax.devices()[0], "device_kind", "unknown")
    peak = CHIP_PEAK_BF16_FLOPS.get(device_kind)
    mfu_fields = {"device_kind": device_kind}
    if win_flops is not None:
        mfu_fields["forward_flops"] = win_flops
        if on_tpu and peak:
            forward_s = iters / iters_per_sec
            mfu_fields.update({
                "mfu": round(win_flops / forward_s / peak, 4),
                "chip_peak_bf16_flops": peak,
            })

    rec = {
        "metric": f"refinement_iters_per_sec_per_chip@{height}x{width}",
        "value": round(iters_per_sec, 2),
        "unit": "iters/s",
        # conservative: the headline amortizes the whole forward incl.
        # the DexiNed+encoder prelude over the 32 iterations, while the
        # 320 it/s denominator is an upstream-RAFT estimate WITHOUT the
        # dual edge stream or DexiNed the v5 model also runs.
        # On a CPU fallback this ratio is diagnostic only (wrong
        # platform, reduced geometry) — `fallback: true` marks it so.
        "vs_baseline": round(iters_per_sec / BASELINE_ITERS_PER_SEC, 3),
        # the record must be self-describing: a CPU fallback line must
        # never be mistaken for a catastrophic TPU regression
        "platform": platform,
        "fallback": not on_tpu,
        # the denominator is an ESTIMATE from upstream-RAFT FPS, not a
        # measured A100 number (none exists in the reference's record)
        "baseline_kind": "estimate",
        "baseline_iters_per_sec": BASELINE_ITERS_PER_SEC,
        # measured same-silicon framework anchor: flax v5 forward vs the
        # reference's torch v5 forward on this host's CPU, same process,
        # same geometry (scripts/torch_cpu_anchor.py, docs/perf.md) —
        # read from the measurement's own log so the record can never
        # drift from its source; absent if the anchor was never run
        **_cpu_anchor_fields(),
        # best-known ON-CHIP state, carried ONLY on fallback records so
        # they self-describe rather than read as a 400x regression —
        # captured by the unattended measurement queue on the r4 healed
        # tunnel at this exact workload (full 440x1024 geometry, the
        # same code path the driver runs). A genuine platform=tpu
        # record must carry its own measured numbers, never these
        # constants beside (possibly contradicting) them.
        **({"builder_tpu_reference": {
            "forward_ms": 100.0,
            "end_to_end_iters_per_sec": 319.9,
            "loop_only_iters_per_sec": 434.8,
            "provenance": "r4 queue record, "
                          "logs/tpu_queue_r4/bench_record.log; "
                          "forward/end-to-end measured on the "
                          "allpairs/subpixel leg, loop-only on the "
                          "allpairs/transpose leg of the same run "
                          "(upconv only affects the prelude, so the "
                          "marginal loop rate is upconv-independent "
                          "by construction)",
        }} if not on_tpu else {}),
        **mfu_fields,
        "iters": iters,
        "corr_impl": impl,
        # what --corr_impl auto WOULD resolve to on this record's
        # platform (config.resolve_corr_impl) — eval/serve print it but
        # records never carried it, so cross-box A/Bs had to infer the
        # production config from the platform field. Distinct from
        # corr_impl: the sweep's WINNER vs the auto-resolution.
        "corr_impl_resolved": resolve_corr_impl("auto", platform)[0],
        # the winning config's pyramid storage precision and fused-step
        # flag (ISSUE 8): together with corr_impl/dexined_upconv these
        # four keys fully name the headline configuration
        "corr_dtype": dtype_best,
        "fused_update": fused_best,
        "dexined_upconv": upconv_best,
        "loop_only_iters_per_sec": (round(loop_ips, 2) if loop_ips
                                    else None),
        # marginal refinement-loop rate (prelude EXCLUDED) over the
        # whole-forward baseline estimate — numerator and denominator
        # are deliberately asymmetric; named so it cannot read as the
        # end-to-end headline speedup
        "loop_only_vs_whole_forward_baseline": (
            round(loop_ips / BASELINE_ITERS_PER_SEC, 3) if loop_ips
            else None),
        "allpairs_iters_per_sec": round(allpairs_ips, 2),
        "local_corr_iters_per_sec": local_ips,
        "pallas_corr_iters_per_sec": diag.get("pallas_iters_per_sec"),
        "flash_corr_iters_per_sec": diag.get("flash_iters_per_sec"),
        **diag,
    }
    validate_record(rec)  # schema pin — a drifted record fails loudly
    # flush: stdout is a block-buffered pipe under the watchdog
    # parent; if JAX teardown hangs after this point (observed with
    # a dead relay), an unflushed record would die in the buffer and
    # the parent would discard a completed measurement
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
